"""Resumable token streams + worker-tick watchdog tests
(serve/streams.py, the /generate/{id}/stream endpoint, and the
``PENROZ_TICK_WATCHDOG_MS`` readiness signal in serve/decode_scheduler.py).

The load-bearing contract is exactly-once across the reconnect seam: a
client that drops mid-stream and reattaches with ``from_seq`` sees every
sequence number exactly once — some replayed from the bounded ring, some
live — with no duplicates and no gaps, while the generation itself never
stopped.  The flip side is honored too: with no detach grace configured
the pre-existing cancel-on-disconnect behavior is unchanged, and an
expired grace fires the ordinary cancellation path under strict
memledger audits.
"""

import asyncio
import json
import time

import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}


@pytest.fixture(autouse=True)
def _streams_registry(workdir):
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import decode_scheduler, qos, streams
    from penroz_tpu.utils import faults
    faults.reset()
    qos.reset()
    streams.reset()
    KV.reset_unpin_underflow_count()
    yield
    decode_scheduler.reset()
    streams.reset()
    faults.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("streamgpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def client(workdir):
    from penroz_tpu.serve import app as app_mod
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    from aiohttp.test_utils import TestClient, TestServer
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield client, loop
    loop.run_until_complete(client.close())
    loop.close()


def _json(client_loop, method, path, **kw):
    client, loop = client_loop

    async def go():
        resp = await client.request(method, path, **kw)
        body = await resp.read()
        return resp.status, (json.loads(body) if body else None)

    return loop.run_until_complete(go())


def _gen_payload(**overrides):
    payload = {"model_id": "streamgpt", "input": [[1, 2, 3]],
               "block_size": BLOCK, "max_new_tokens": 6,
               "temperature": 0.0}
    payload.update(overrides)
    return payload


def _parse_seq_lines(text):
    """``seq:value`` resume-endpoint lines → [(seq, value-str), ...]."""
    out = []
    for line in text.strip().split("\n"):
        seq, value = line.split(":", 1)
        out.append((int(seq), value))
    return out


class _Req:
    cancelled = False


# -- unit layer --------------------------------------------------------------

def test_ring_resume_seam_is_exactly_once(monkeypatch):
    """resume() returns the ring backlog and subscribes the queue under
    ONE lock: backlog ∪ live-queue covers every seq >= from_seq exactly
    once, including events published after the reattach."""
    from penroz_tpu.serve import streams
    sess = streams.StreamSession("r1", _Req())
    for i in range(5):
        sess.publish("token", 100 + i)
    loop = asyncio.new_event_loop()
    try:
        q = asyncio.Queue()
        backlog = sess.resume(loop, q, 2)
        assert [(s, v) for s, _, v in backlog] == [(2, 102), (3, 103),
                                                   (4, 104)]
        sess.publish("token", 105)
        sess.publish("done", None)
        loop.run_until_complete(asyncio.sleep(0.01))
        live = []
        while not q.empty():
            live.append(q.get_nowait())
        assert [(s, k) for s, k, _ in live] == [(5, "token"), (6, "done")]
        seqs = [e[0] for e in backlog] + [e[0] for e in live]
        assert seqs == sorted(set(seqs)) == list(range(2, 7))
        assert sess.snapshot()["resumes"] == 1
    finally:
        loop.close()


def test_replay_gap_and_expiry_are_typed_errors(monkeypatch):
    """Asking for seqs the bounded ring evicted — or reattaching after
    the detach grace already cancelled the request — raises
    ReplayGapError (the HTTP 410), never a silent skip."""
    from penroz_tpu.serve import streams
    monkeypatch.setenv(streams.REPLAY_ENV, "4")
    sess = streams.StreamSession("r2", _Req())
    for i in range(10):
        sess.publish("token", i)
    loop = asyncio.new_event_loop()
    try:
        with pytest.raises(streams.ReplayGapError):
            sess.resume(loop, asyncio.Queue(), 0)
        backlog = sess.resume(loop, asyncio.Queue(), 6)
        assert [e[0] for e in backlog] == [6, 7, 8, 9]

        # grace expiry flips req.cancelled and poisons later resumes
        monkeypatch.setenv(streams.DETACH_MS_ENV, "30")
        req = _Req()
        sess2 = streams.StreamSession("r3", req)
        sess2.publish("token", 0)
        assert sess2.try_detach() is True
        deadline = time.monotonic() + 5
        while not req.cancelled:
            assert time.monotonic() < deadline, "grace never expired"
            time.sleep(0.01)
        assert sess2.expired is True
        with pytest.raises(streams.ReplayGapError):
            sess2.resume(loop, asyncio.Queue(), 0)
    finally:
        loop.close()


def test_zero_grace_means_cancel_on_disconnect(monkeypatch):
    """The default (no PENROZ_STREAM_DETACH_MS) keeps the pre-existing
    behavior: try_detach refuses and the caller runs the cancel path."""
    from penroz_tpu.serve import streams
    monkeypatch.delenv(streams.DETACH_MS_ENV, raising=False)
    sess = streams.StreamSession("r4", _Req())
    sess.publish("token", 0)
    assert sess.try_detach() is False
    # terminal streams refuse too, whatever the grace says
    monkeypatch.setenv(streams.DETACH_MS_ENV, "60000")
    sess.publish("done", None)
    assert sess.try_detach() is False


# -- HTTP layer --------------------------------------------------------------

def test_http_resume_replays_completed_stream(client, gpt_model,
                                              monkeypatch):
    """A finished stream lingers: GET /generate/{id}/stream?from_seq=0
    replays the whole ring as ``seq:value`` lines ending in ``N:done``,
    token-for-token equal to what the live stream delivered."""
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    test_client, loop = client

    async def go():
        resp = await test_client.post(
            "/generate/", json=_gen_payload(stream=True),
            headers={"X-Request-Id": "resume-a"})
        assert resp.status == 200
        return (await resp.read()).decode()

    streamed = [int(t) for t in
                loop.run_until_complete(go()).strip().split("\n")]

    async def resume(rid, from_seq):
        resp = await test_client.get(f"/generate/{rid}/stream",
                                     params={"from_seq": str(from_seq)})
        return resp.status, (await resp.read()).decode()

    status, text = loop.run_until_complete(resume("resume-a", 0))
    assert status == 200
    events = _parse_seq_lines(text)
    assert [s for s, _ in events] == list(range(len(streamed) + 1))
    assert [int(v) for _, v in events[:-1]] == streamed
    assert events[-1][1] == "done"
    # mid-stream reattach point: only the suffix replays
    status, text = loop.run_until_complete(resume("resume-a", 3))
    assert status == 200
    assert [s for s, _ in _parse_seq_lines(text)] == \
        list(range(3, len(streamed) + 1))

    # error surface: unknown id 404, junk from_seq 422
    status, _ = loop.run_until_complete(resume("never-was", 0))
    assert status == 404
    async def bad():
        resp = await test_client.get("/generate/resume-a/stream",
                                     params={"from_seq": "soon"})
        return resp.status
    assert loop.run_until_complete(bad()) == 422


def test_http_resume_behind_ring_is_410(client, gpt_model, monkeypatch):
    """A reconnect that fell further behind than PENROZ_STREAM_REPLAY is
    refused with 410 Gone — resuming would skip tokens silently."""
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv("PENROZ_STREAM_REPLAY", "2")
    test_client, loop = client

    async def go():
        resp = await test_client.post(
            "/generate/", json=_gen_payload(stream=True),
            headers={"X-Request-Id": "tiny-ring"})
        await resp.read()
        gone = await test_client.get("/generate/tiny-ring/stream",
                                     params={"from_seq": "0"})
        body = await gone.read()
        return gone.status, body.decode()

    status, body = loop.run_until_complete(go())
    assert status == 410 and "replay ring" in body


def test_http_disconnect_detach_reconnect_exactly_once(client, gpt_model,
                                                       monkeypatch):
    """THE acceptance path: client drops mid-stream with a detach grace
    configured → decode keeps running (no cancel) → reconnect at the
    next unseen seq → replayed ring + live tail cover every seq exactly
    once and the union equals the uninterrupted greedy stream."""
    from penroz_tpu.serve import streams
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(streams.DETACH_MS_ENV, "60000")
    # slow each decode step down so the disconnect happens mid-flight
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@30")
    test_client, loop = client
    payload = _gen_payload(max_new_tokens=8, stream=True)
    rid = "reconnect-1"

    async def drop_then_resume():
        resp = await test_client.post("/generate/", json=payload,
                                      headers={"X-Request-Id": rid})
        assert resp.status == 200
        line = await resp.content.readline()
        first = int(line.decode().strip())
        resp.close()              # hard disconnect, handler cancelled
        # the server notices at its next write and detaches instead of
        # cancelling; the generation (and the ring) keep going
        deadline = time.monotonic() + 30
        while True:
            sess = streams.STREAMS.get(rid)
            assert sess is not None, \
                "stream was discarded => cancel path ran"
            snap = sess.snapshot()
            if snap["detached"] or snap["terminal"]:
                break
            assert time.monotonic() < deadline, snap
            await asyncio.sleep(0.01)
        assert not sess.req.cancelled
        resumed = await test_client.get(f"/generate/{rid}/stream",
                                        params={"from_seq": "1"})
        assert resumed.status == 200
        return first, (await resumed.read()).decode()

    first, text = loop.run_until_complete(drop_then_resume())
    events = _parse_seq_lines(text)
    assert [s for s, _ in events] == list(range(1, 9))   # 7 tokens + done
    assert events[-1][1] == "done"
    resumed = [int(v) for _, v in events[:-1]]

    # the union equals the uninterrupted greedy stream
    monkeypatch.delenv(faults.ENV)
    monkeypatch.delenv("PENROZ_CONTINUOUS_BATCHING")
    status, legacy = _json(client, "POST", "/generate/",
                           json=_gen_payload(max_new_tokens=8))
    assert status == 200
    assert [first] + resumed == legacy["tokens"][3:]
    stats = streams.STREAMS.stats()
    assert stats["detaches"] >= 1 and stats["resumes"] >= 1
    assert stats["expired"] == 0


def test_http_detach_grace_expiry_cancels(client, gpt_model, monkeypatch):
    """When no reconnect arrives inside the grace the ordinary
    cancellation path fires: the row is retired early (strict memledger
    audits the unwind) and later resumes are refused."""
    from penroz_tpu.serve import streams
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(streams.DETACH_MS_ENV, "150")
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@40")
    test_client, loop = client
    rid = "abandoned-1"

    async def drop_and_expire():
        resp = await test_client.post(
            "/generate/", json=_gen_payload(max_new_tokens=12, stream=True),
            headers={"X-Request-Id": rid})
        await resp.content.readline()
        resp.close()
        deadline = time.monotonic() + 30
        while streams.STREAMS.stats()["expired"] == 0:
            assert time.monotonic() < deadline, streams.STREAMS.stats()
            await asyncio.sleep(0.02)
        resumed = await test_client.get(f"/generate/{rid}/stream",
                                        params={"from_seq": "0"})
        await resumed.read()
        return resumed.status

    assert loop.run_until_complete(drop_and_expire()) in (404, 410)
    # the engine retired the row long before 12 tokens' worth of sleeps
    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200 and stats["streams"]["expired"] >= 1


def test_stream_resume_fault_site(client, gpt_model, monkeypatch):
    """An injected stream.resume failure surfaces as the HTTP 500 while
    the ring (and a later reattach) stay intact."""
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    test_client, loop = client

    async def go():
        resp = await test_client.post(
            "/generate/", json=_gen_payload(stream=True),
            headers={"X-Request-Id": "faulty-resume"})
        return (await resp.read()).decode()

    streamed = [int(t) for t in
                loop.run_until_complete(go()).strip().split("\n")]
    monkeypatch.setenv(faults.ENV, "stream.resume:raise@1")

    async def resume():
        resp = await test_client.get("/generate/faulty-resume/stream",
                                     params={"from_seq": "0"})
        return resp.status, (await resp.read()).decode()

    status, _ = loop.run_until_complete(resume())
    assert status == 500
    # the fault was one-shot; the stream is still resumable afterwards
    status, text = loop.run_until_complete(resume())
    assert status == 200
    events = _parse_seq_lines(text)
    assert [int(v) for _, v in events[:-1]] == streamed


# -- worker-tick watchdog ----------------------------------------------------

def test_watchdog_flags_wedged_tick_and_recovers(client, gpt_model,
                                                 monkeypatch):
    """A tick dispatch that outlives PENROZ_TICK_WATCHDOG_MS flips the
    engine's ``stuck`` verdict, names it in /readyz (503) and
    ``engines_stuck``, and records ONE ``watchdog`` flight-recorder
    entry; when the dispatch finally returns everything clears."""
    from penroz_tpu.serve import memledger
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv("PENROZ_TICK_WATCHDOG_MS", "100")
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@300")
    memledger.FLIGHT_RECORDER.reset()
    test_client, loop = client

    async def go():
        gen = asyncio.ensure_future(test_client.post(
            "/generate/", json=_gen_payload(max_new_tokens=4)))
        # give the worker time to get wedged inside a tick dispatch
        await asyncio.sleep(0.6)
        ready = await test_client.get("/readyz")
        ready_body = await ready.json()
        stats = await (await test_client.get("/serving_stats/")).json()
        resp = await gen
        body = await resp.json()
        assert resp.status == 200, body
        return ready.status, ready_body, stats

    ready_status, ready_body, stats = loop.run_until_complete(go())
    assert ready_status == 503
    assert ready_body["ready"] is False
    assert ready_body["stuck_engines"] == ["streamgpt"]
    assert stats["engines_stuck"] == 1
    assert any(e["stuck"] for e in stats["engines"])
    dump = memledger.FLIGHT_RECORDER.dump()
    watchdog_entries = [e for e in dump["entries"]
                        if e["reason"] == "watchdog"]
    assert len(watchdog_entries) == 1
    assert watchdog_entries[0]["model_id"] == "streamgpt"

    # once the wedged dispatch finally returns the verdict clears with
    # no reset — poll past the tail of the in-flight tick
    monkeypatch.delenv(faults.ENV)
    deadline = time.monotonic() + 30
    while True:
        status, body = _json(client, "GET", "/readyz")
        if status == 200:
            break
        assert time.monotonic() < deadline, body
        time.sleep(0.05)
    assert body["stuck_engines"] == []
    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200 and stats["engines_stuck"] == 0


def test_watchdog_off_by_default(client, gpt_model, monkeypatch):
    """Without PENROZ_TICK_WATCHDOG_MS even a slow tick is never flagged
    — the watchdog is strictly opt-in."""
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.delenv("PENROZ_TICK_WATCHDOG_MS", raising=False)
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@200")
    test_client, loop = client

    async def go():
        gen = asyncio.ensure_future(test_client.post(
            "/generate/", json=_gen_payload(max_new_tokens=3)))
        await asyncio.sleep(0.4)
        ready = await test_client.get("/readyz")
        body = await ready.json()
        resp = await gen
        await resp.read()
        return ready.status, body

    status, body = loop.run_until_complete(go())
    assert status == 200 and body["stuck_engines"] == []
