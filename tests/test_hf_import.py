"""HuggingFace import tests with locally constructed torch models (offline).

Goes beyond the reference's key-set assertions (test_neural_net_model.py HF
mocks): imports weights through the real mapping path and checks our JAX
forward produces the same logits as the torch model."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

# CI tier: heavier compiles (see pyproject markers / ci.yml shards).
pytestmark = pytest.mark.runtime


def _tiny_gpt2():
    from transformers import GPT2Config, GPT2LMHeadModel
    config = GPT2Config(vocab_size=96, n_positions=32, n_embd=16, n_layer=2,
                        n_head=2, activation_function="gelu_new",
                        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    return config, GPT2LMHeadModel(config).eval()


def _tiny_gemma2():
    from transformers import Gemma2Config, Gemma2ForCausalLM
    config = Gemma2Config(vocab_size=96, hidden_size=16, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          head_dim=8, intermediate_size=32,
                          max_position_embeddings=64, rope_theta=10000.0,
                          attn_logit_softcapping=None,
                          final_logit_softcapping=None,
                          query_pre_attn_scalar=8, sliding_window=64,
                          attention_dropout=0.0,
                          hidden_activation="gelu_pytorch_tanh")
    torch.manual_seed(0)
    return config, Gemma2ForCausalLM(config).eval()


def _save_checkpoint(workdir, torch_model, name) -> str:
    """Serialize the oracle model as a real safetensors checkpoint dir —
    every import test then exercises the torch-free load path end to end
    (config.json + model.safetensors, tied weights omitted by HF)."""
    ckpt = str(workdir / f"hf_{name}")
    torch_model.to(torch.bfloat16).save_pretrained(ckpt,
                                                   safe_serialization=True)
    return ckpt


def _import_model(workdir, config, torch_model, model_id):
    del config  # read back from the checkpoint's config.json
    ckpt = _save_checkpoint(workdir, torch_model, model_id)
    return NeuralNetworkModel.from_huggingface(model_id, ckpt)


def test_gpt2_import_logit_parity(workdir):
    config, torch_model = _tiny_gpt2()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "gpt2-tiny")
    assert model.status["code"] == "Imported"
    import jax.numpy as jnp
    assert model.dtype == jnp.bfloat16

    acts, cost, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                              jnp.asarray(tokens, jnp.int32),
                                              skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    # bf16 weights end-to-end: compare softmax-invariant shifted logits
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    # argmax parity position-by-position
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8


def test_gpt2_import_roundtrip_and_generate(workdir):
    config, torch_model = _tiny_gpt2()
    _import_model(workdir, config, torch_model, "gpt2-rt")
    loaded = NeuralNetworkModel.deserialize("gpt2-rt")
    assert loaded.status["code"] == "Imported"
    tokens = loaded.generate_tokens([[1, 2, 3]], block_size=16,
                                    max_new_tokens=4, temperature=0.0)
    assert len(tokens) == 7
    assert all(0 <= t < 96 for t in tokens)


def test_gemma2_import_logit_parity(workdir):
    config, torch_model = _tiny_gemma2()
    tokens = np.array([[3, 17, 42, 8]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "gemma-tiny")
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.2)


def test_import_rejects_mismatched_state_dict(workdir):
    """A checkpoint missing a param key fails loudly (strict key-set
    equality, reference load_state_dict(strict=True) analog)."""
    from safetensors.numpy import load_file, save_file
    _, torch_model = _tiny_gpt2()
    ckpt = _save_checkpoint(workdir, torch_model, "broken")
    path = f"{ckpt}/model.safetensors"
    sd = load_file(path)
    sd.pop("transformer.h.1.mlp.c_proj.bias")
    save_file(sd, path)
    with pytest.raises(KeyError):
        NeuralNetworkModel.from_huggingface("broken", ckpt)


def test_import_is_torch_free(workdir, monkeypatch):
    """/import/ of a local safetensors GPT-2 succeeds with torch import
    blocked — the VERDICT r2 acceptance bar (safetensors→numpy direct
    load, SURVEY §2.3; torch remains only this file's oracle)."""
    import sys
    import transformers.configuration_utils as tcu
    _, torch_model = _tiny_gpt2()
    ckpt = _save_checkpoint(workdir, torch_model, "notorch")
    # None in sys.modules makes any fresh `import torch` raise ImportError;
    # is_torch_available must lie too or transformers eagerly converts the
    # config.json torch_dtype string (it skips that in a real no-torch env)
    monkeypatch.setitem(sys.modules, "torch", None)
    monkeypatch.setattr(tcu, "is_torch_available", lambda: False)
    model = NeuralNetworkModel.from_huggingface("notorch", ckpt)
    assert model.status["code"] == "Imported"
    tokens = model.generate_tokens([[1, 2, 3]], block_size=16,
                                   max_new_tokens=3, temperature=0.0)
    assert len(tokens) == 6


def test_import_unprefixed_base_model_checkpoint(workdir):
    """The original ``gpt2`` hub checkpoints were saved from the bare base
    model — keys lack the ``transformer.`` prefix and carry extra mask
    buffers; the loader canonicalizes them (hf_loader._normalize)."""
    from safetensors.numpy import load_file, save_file
    _, torch_model = _tiny_gpt2()
    ckpt = _save_checkpoint(workdir, torch_model, "rawgpt2")
    path = f"{ckpt}/model.safetensors"
    sd = load_file(path)
    raw = {k.removeprefix("transformer."): v for k, v in sd.items()
           if not k.startswith("lm_head.")}
    raw["h.0.attn.bias"] = np.tril(np.ones((32, 32), np.float32))[None, None]
    save_file(raw, path)
    model = NeuralNetworkModel.from_huggingface("rawgpt2", ckpt)
    assert model.status["code"] == "Imported"
    assert model.params["layers.0.0.weight"].shape == (96, 16)


def test_bin_only_checkpoint_without_torch_is_clear_error(workdir,
                                                          monkeypatch):
    import sys
    from penroz_tpu.models import hf_loader
    _, torch_model = _tiny_gpt2()
    ckpt = str(workdir / "binonly")
    torch_model.save_pretrained(ckpt, safe_serialization=False)
    monkeypatch.setitem(sys.modules, "torch", None)
    with pytest.raises(RuntimeError, match="safetensors"):
        hf_loader.load_state_dict(ckpt)


def _tiny_llama():
    from transformers import LlamaConfig, LlamaForCausalLM
    config = LlamaConfig(vocab_size=96, hidden_size=16, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         head_dim=4, intermediate_size=32,
                         max_position_embeddings=64, rope_theta=10000.0,
                         attention_dropout=0.0, hidden_act="silu",
                         attention_bias=False, mlp_bias=False,
                         tie_word_embeddings=False)
    torch.manual_seed(0)
    return config, LlamaForCausalLM(config).eval()


def _tiny_qwen2():
    from transformers import Qwen2Config, Qwen2ForCausalLM
    config = Qwen2Config(vocab_size=96, hidden_size=16, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         intermediate_size=32, max_position_embeddings=64,
                         rope_theta=10000.0, attention_dropout=0.0,
                         hidden_act="silu", tie_word_embeddings=True)
    torch.manual_seed(0)
    return config, Qwen2ForCausalLM(config).eval()


def test_llama_import_logit_parity(workdir):
    """Llama family (beyond reference parity): straight RMSNorm copy, no
    embedding scale, untied lm_head, GQA + RoPE."""
    config, torch_model = _tiny_llama()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "llama-tiny")
    assert model.status["code"] == "Imported"
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8


def test_qwen2_import_logit_parity_and_generate(workdir):
    """Qwen2: hardcoded QKV bias (concat-mapped), no o bias, tied lm_head."""
    config, torch_model = _tiny_qwen2()
    tokens = np.array([[5, 9, 63, 2]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "qwen-tiny")
    import jax.numpy as jnp
    assert "layers.1.attn_block.1.bias" in model.params  # qkv bias mapped
    assert "layers.1.attn_block.3.bias" not in model.params  # o has none
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    gen = NeuralNetworkModel.deserialize("qwen-tiny").generate_tokens(
        [[1, 2, 3]], block_size=16, max_new_tokens=4, temperature=0.0)
    assert len(gen) == 7 and all(0 <= t < 96 for t in gen)


def test_llama3_rope_scaling_logit_parity(workdir):
    """Llama 3.1-style rope_scaling (llama3 inverse-frequency rescale) must
    match the torch implementation's logits, not just import."""
    from transformers import LlamaConfig, LlamaForCausalLM
    config = LlamaConfig(vocab_size=96, hidden_size=16, num_hidden_layers=2,
                         num_attention_heads=4, num_key_value_heads=2,
                         head_dim=4, intermediate_size=32,
                         max_position_embeddings=128, rope_theta=10000.0,
                         attention_dropout=0.0, tie_word_embeddings=False,
                         rope_scaling={"rope_type": "llama3", "factor": 8.0,
                                       "low_freq_factor": 1.0,
                                       "high_freq_factor": 4.0,
                                       "original_max_position_embeddings": 16})
    torch.manual_seed(0)
    torch_model = LlamaForCausalLM(config).eval()
    # positions past original_max_position_embeddings exercise the rescale
    tokens = np.arange(24, dtype=np.int64)[None, :] % 96
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "llama31-tiny")
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)


def test_llama_unsupported_rope_scaling_rejected():
    """Non-llama3 active scaling types (yarn, dynamic) must fail the import
    loudly — importing with them ignored would silently produce wrong
    logits."""
    from transformers import LlamaConfig
    config = LlamaConfig(vocab_size=96, hidden_size=16, num_hidden_layers=1,
                         num_attention_heads=4, num_key_value_heads=2,
                         head_dim=4, intermediate_size=32,
                         rope_scaling={"rope_type": "yarn", "factor": 4.0})
    with pytest.raises(ValueError, match="rope_scaling"):
        Mapper.from_hf_config(config)


def test_dsl_rope_scaling_validated_at_build():
    """rope_scaling is validated where the DSL reaches the module (POST
    /model/ → 400), not only in the HF importer — a yarn dict must not
    silently run the llama3 formula."""
    from penroz_tpu.ops.modules import CausalSelfAttention
    with pytest.raises(ValueError, match="not supported"):
        CausalSelfAttention(num_heads=2, rope_theta=1e4,
                            rope_scaling={"rope_type": "yarn", "factor": 4.0})
    with pytest.raises(ValueError, match="missing keys"):
        CausalSelfAttention(num_heads=2, rope_theta=1e4,
                            rope_scaling={"rope_type": "llama3"})


def test_mistral_sliding_window_logit_parity(workdir):
    """Mistral imports with REAL windowed attention: logits must match
    torch at sequence lengths beyond the sliding window (the reference
    keeps all attention full causal and would diverge here)."""
    from transformers import MistralConfig, MistralForCausalLM
    config = MistralConfig(vocab_size=96, hidden_size=16, num_hidden_layers=2,
                           num_attention_heads=4, num_key_value_heads=2,
                           head_dim=4, intermediate_size=32,
                           max_position_embeddings=128, rope_theta=10000.0,
                           attention_dropout=0.0, sliding_window=8,
                           tie_word_embeddings=False)
    torch.manual_seed(0)
    torch_model = MistralForCausalLM(config).eval()
    tokens = (np.arange(24, dtype=np.int64)[None, :] * 7) % 96  # 24 > 8
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "mistral-tiny")
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    # windowed decode path works too
    gen = NeuralNetworkModel.deserialize("mistral-tiny").generate_tokens(
        [[1, 2, 3]], block_size=32, max_new_tokens=12, temperature=0.0)
    assert len(gen) == 15


def test_gemma2_sliding_layers_logit_parity(workdir):
    """Gemma-2 layer_types: sliding layers get windowed attention, full
    layers stay full — parity vs torch past the window."""
    from transformers import Gemma2Config, Gemma2ForCausalLM
    config = Gemma2Config(vocab_size=96, hidden_size=16, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          head_dim=8, intermediate_size=32,
                          max_position_embeddings=64, rope_theta=10000.0,
                          attn_logit_softcapping=None,
                          final_logit_softcapping=None,
                          query_pre_attn_scalar=8, sliding_window=8,
                          layer_types=["sliding_attention", "full_attention"],
                          attention_dropout=0.0,
                          hidden_activation="gelu_pytorch_tanh")
    torch.manual_seed(0)
    torch_model = Gemma2ForCausalLM(config).eval()
    tokens = (np.arange(20, dtype=np.int64)[None, :] * 5) % 96  # 20 > 8
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "gemma2-sw")
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.2)


def test_qwen2_max_window_layers_gating():
    """Qwen2 use_sliding_window windows only the layers HF marks
    'sliding_attention' (max_window_layers full layers first), not all."""
    from transformers import Qwen2Config
    config = Qwen2Config(vocab_size=96, hidden_size=16, num_hidden_layers=4,
                         num_attention_heads=4, num_key_value_heads=2,
                         intermediate_size=32, use_sliding_window=True,
                         sliding_window=8, max_window_layers=2)
    layers = Mapper.from_hf_config(config)
    blocks = [l["transformerblock"] for l in layers if "transformerblock" in l]
    windows = [b["attn_block"]["sequential"][2]["attention"]
               .get("sliding_window") for b in blocks]
    expected = [8 if lt == "sliding_attention" else None
                for lt in config.layer_types]
    assert windows == expected
    assert None in windows  # some layers stay full...
    assert 8 in windows     # ...and some are windowed


def test_rope_scaling_numeric_validation():
    """Degenerate llama3 scaling numbers NaN every logit via the band
    smoothing's (high - low) division — reject at build time."""
    from penroz_tpu.ops.modules import CausalSelfAttention
    base = {"rope_type": "llama3", "factor": 8.0,
            "original_max_position_embeddings": 8192}
    with pytest.raises(ValueError, match="high_freq_factor"):
        CausalSelfAttention(num_heads=2, rope_theta=1e4,
                            rope_scaling={**base, "low_freq_factor": 2.0,
                                          "high_freq_factor": 2.0})
    with pytest.raises(ValueError, match="factor must be"):
        CausalSelfAttention(num_heads=2, rope_theta=1e4,
                            rope_scaling={**base, "factor": 0.5})


def _tiny_neox(parallel=True):
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM
    config = GPTNeoXConfig(vocab_size=96, hidden_size=32,
                           num_hidden_layers=2, num_attention_heads=2,
                           intermediate_size=64, rotary_pct=0.25,
                           max_position_embeddings=64,
                           use_parallel_residual=parallel,
                           hidden_act="gelu", attention_dropout=0.0,
                           hidden_dropout=0.0, tie_word_embeddings=False)
    torch.manual_seed(0)
    return config, GPTNeoXForCausalLM(config).eval()


def test_neox_import_logit_parity(workdir):
    """GPT-NeoX/Pythia: parallel-residual blocks, partial rotary
    (rotary_pct), per-head-interleaved QKV de-interleaved, untied
    embed_out (beyond reference parity)."""
    config, torch_model = _tiny_neox()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "neox-tiny")
    assert model.status["code"] == "Imported"
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8


def test_neox_sequential_residual_logit_parity(workdir):
    """use_parallel_residual=False checkpoints get the ordinary
    sequential-residual block and still match torch."""
    config, torch_model = _tiny_neox(parallel=False)
    tokens = np.array([[5, 1, 60, 22]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "neox-seq")
    import jax.numpy as jnp
    assert "parallelresidual" not in str(model.layers_dsl)
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)


def _greedy_rollout(model, ctx, steps, block=16):
    """Token-by-token UNCACHED argmax continuation of ``ctx`` (the oracle
    the KV-cached greedy generate must match)."""
    import jax.numpy as jnp
    ctx = list(ctx)
    for _ in range(steps):
        acts, _, _, _ = model.arch.jit_forward(
            model.params, model.buffers,
            jnp.asarray([ctx[-block:]], jnp.int32), skip_softmax=True)
        logits = np.asarray(acts[-1], np.float32)
        if logits.ndim == 3:
            logits = logits[:, -1, :]
        ctx.append(int(logits.argmax(-1)[0]))
    return ctx


def test_neox_cached_generate_matches_uncached(workdir):
    """Partial rotary must behave identically through the KV-cached decode
    path (rope offset applied to the rotary dims only): greedy cached
    generation must equal a token-by-token UNCACHED argmax rollout."""
    import jax.numpy as jnp
    config, torch_model = _tiny_neox()
    model = _import_model(workdir, config, torch_model, "neox-gen")
    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert len(toks) == 9
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def test_neox_rope_scaling_rejected():
    """Active rope_scaling on gpt_neox is unsupported — reject at DSL build
    rather than importing with it silently ignored (wrong logits)."""
    from penroz_tpu.models.dsl import Mapper

    class Cfg:
        model_type = "gpt_neox"
        hidden_size = 32
        num_hidden_layers = 1
        num_attention_heads = 2
        vocab_size = 96
        rope_scaling = {"type": "linear", "factor": 2.0}

    with pytest.raises(ValueError, match="rope_scaling"):
        Mapper.from_hf_config(Cfg())


def test_neox_attention_bias_false_logit_parity(workdir):
    """attention_bias=False checkpoints carry no qkv/dense biases; the DSL
    must build bias-free linears and still match torch."""
    from transformers import GPTNeoXConfig, GPTNeoXForCausalLM
    config = GPTNeoXConfig(vocab_size=96, hidden_size=32,
                           num_hidden_layers=2, num_attention_heads=2,
                           intermediate_size=64, rotary_pct=0.25,
                           max_position_embeddings=64,
                           use_parallel_residual=True, hidden_act="gelu",
                           attention_bias=False, attention_dropout=0.0,
                           hidden_dropout=0.0, tie_word_embeddings=False)
    torch.manual_seed(1)
    torch_model = GPTNeoXForCausalLM(config).eval()
    tokens = np.array([[7, 30, 2, 19]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "neox-nobias")
    import jax.numpy as jnp
    assert "layers.1.0.1.bias" not in model.params
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)


def _tiny_phi():
    from transformers import PhiConfig, PhiForCausalLM
    config = PhiConfig(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                       num_attention_heads=2, intermediate_size=64,
                       partial_rotary_factor=0.5,
                       max_position_embeddings=64, hidden_act="gelu_new",
                       attention_dropout=0.0, resid_pdrop=0.0,
                       embd_pdrop=0.0, tie_word_embeddings=False)
    torch.manual_seed(0)
    return config, PhiForCausalLM(config).eval()


def test_phi_import_logit_parity(workdir):
    """Phi-1/1.5/2: parallel attn+MLP branches sharing ONE input LayerNorm
    (residual -> ln -> summation nesting), partial rotary, biased
    projections and a biased lm_head (beyond reference parity)."""
    config, torch_model = _tiny_phi()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "phi-tiny")
    assert model.status["code"] == "Imported"
    assert "summation" in str(model.layers_dsl)
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8


# the cached-vs-uncached generate seam is pinned fast by the NeoX variant
@pytest.mark.slow
def test_phi_cached_generate_matches_uncached(workdir):
    """Phi partial rotary + biased fused QKV through the KV-cached decode
    path: greedy cached generation == uncached argmax rollout."""
    import jax.numpy as jnp
    config, torch_model = _tiny_phi()
    model = _import_model(workdir, config, torch_model, "phi-gen")
    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert len(toks) == 9
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def test_phi_qk_layernorm_rejected():
    from transformers import PhiConfig
    from penroz_tpu.models.dsl import Mapper
    config = PhiConfig(vocab_size=96, hidden_size=32, num_hidden_layers=1,
                       num_attention_heads=2, qk_layernorm=True)
    with pytest.raises(ValueError, match="qk_layernorm"):
        Mapper.from_hf_config(config)
    tied = PhiConfig(vocab_size=96, hidden_size=32, num_hidden_layers=1,
                     num_attention_heads=2, tie_word_embeddings=True)
    with pytest.raises(ValueError, match="tie_word_embeddings"):
        Mapper.from_hf_config(tied)
    # partial_rotary_factor=0.0 disables rope instead of being coerced
    norope = PhiConfig(vocab_size=96, hidden_size=32, num_hidden_layers=1,
                       num_attention_heads=2, partial_rotary_factor=0.0)
    dsl = Mapper.from_hf_config(norope)
    assert "rope_theta" not in __import__("json").dumps(dsl)


def _tiny_qwen3():
    from transformers import Qwen3Config, Qwen3ForCausalLM
    config = Qwen3Config(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                         num_attention_heads=2, num_key_value_heads=1,
                         head_dim=16, intermediate_size=64,
                         max_position_embeddings=64, rope_theta=10000.0,
                         attention_dropout=0.0, tie_word_embeddings=False,
                         use_sliding_window=False)
    torch.manual_seed(0)
    return config, Qwen3ForCausalLM(config).eval()


def test_qwen3_import_logit_parity_and_generate(workdir):
    """Qwen3: llama family + per-head RMS qk-norm (learned (head_dim,)
    weights applied before RoPE) and GQA; cached greedy generation must
    match the uncached argmax rollout through the normalized path."""
    import jax.numpy as jnp
    config, torch_model = _tiny_qwen3()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "qwen3-tiny")
    assert model.status["code"] == "Imported"
    assert any("q_norm" in k for k in model.params), model.params.keys()
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def _tiny_mixtral():
    from transformers import MixtralConfig, MixtralForCausalLM
    config = MixtralConfig(vocab_size=96, hidden_size=32,
                           num_hidden_layers=2, num_attention_heads=2,
                           num_key_value_heads=1, intermediate_size=48,
                           num_local_experts=4, num_experts_per_tok=2,
                           max_position_embeddings=64, rope_theta=10000.0,
                           sliding_window=None, attention_dropout=0.0,
                           router_aux_loss_coef=0.02,
                           tie_word_embeddings=False)
    torch.manual_seed(0)
    return config, MixtralForCausalLM(config).eval()


# slow lane (tier1_budget): MoE forward math stays fast via test_moe and
# the qwen2-moe import gate; stacked-expert import parity rides slow
@pytest.mark.slow
def test_mixtral_import_logit_parity_and_generate(workdir):
    """Mixtral: sparse-MoE MLPs land on our stacked-expert module (dense
    dispatch reproduces HF's softmax->top-k->renormalize routing exactly);
    per-expert w1/w3/w2 stack onto gate/up/down, router gate copies, and
    router_aux_loss_coef rescales (x top_k / n_layers) onto our per-layer Switch form."""
    config, torch_model = _tiny_mixtral()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "mixtral-tiny")
    assert model.status["code"] == "Imported"
    assert any("router.weight" in k for k in model.params)
    # router_aux_loss_coef normalized to HF semantics:
    # 0.02 * top_k(2) / n_layers(2) = 0.02
    assert '"aux_loss_coef": 0.02' in __import__("json").dumps(
        model.layers_dsl)
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def _tiny_olmo2():
    from transformers import Olmo2Config, Olmo2ForCausalLM
    config = Olmo2Config(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                         num_attention_heads=2, num_key_value_heads=1,
                         intermediate_size=64, max_position_embeddings=64,
                         rope_theta=10000.0, attention_dropout=0.0,
                         tie_word_embeddings=False)
    torch.manual_seed(0)
    return config, Olmo2ForCausalLM(config).eval()


# slow lane (tier1_budget): OLMo v1 keeps the family's import parity
# fast; olmo2's unique qk-norm wiring is also pinned by qwen3
@pytest.mark.slow
def test_olmo2_import_logit_parity_and_generate(workdir):
    """OLMo-2: post-norm-only blocks (branch-tail rmsnorms, no input
    norms) and FLAT q/k RMS normalization over the whole projection before
    the head split — cached greedy generate must match the uncached argmax
    rollout through that path."""
    config, torch_model = _tiny_olmo2()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "olmo2-tiny")
    assert model.status["code"] == "Imported"
    assert any("q_norm" in k for k in model.params)
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def test_olmo2_rope_scaling_rejected():
    from transformers import Olmo2Config
    from penroz_tpu.models.dsl import Mapper
    config = Olmo2Config(vocab_size=96, hidden_size=32, num_hidden_layers=1,
                         num_attention_heads=2,
                         rope_scaling={"type": "linear", "factor": 2.0})
    with pytest.raises(ValueError, match="olmo2 rope_scaling"):
        Mapper.from_hf_config(config)


def _tiny_olmo(clip_qkv=None):
    from transformers import OlmoConfig, OlmoForCausalLM
    config = OlmoConfig(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                        num_attention_heads=2, num_key_value_heads=1,
                        intermediate_size=64, max_position_embeddings=64,
                        rope_theta=10000.0, attention_dropout=0.0,
                        clip_qkv=clip_qkv, tie_word_embeddings=False)
    torch.manual_seed(0)
    return config, OlmoForCausalLM(config).eval()


@pytest.mark.parametrize("clip_qkv", [None, pytest.param(0.5, marks=pytest.mark.slow)])
def test_olmo_import_logit_parity(workdir, clip_qkv):
    """OLMo v1: NON-PARAMETRIC LayerNorms (no weights to map at all) and
    optional clip_qkv (fused QKV output clamped to ±clip via the clamp
    DSL entry, shifting the branch's item indices)."""
    config, torch_model = _tiny_olmo(clip_qkv=clip_qkv)
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    tag = "olmo-clip" if clip_qkv else "olmo-tiny"
    model = _import_model(workdir, config, torch_model, tag)
    assert model.status["code"] == "Imported"
    assert not any("layernorm" in k.lower() or ".0.0." in k
                   for k in model.params), \
        [k for k in model.params if ".0.0." in k]
    assert ('"clamp"' in __import__("json").dumps(model.layers_dsl)) == \
        (clip_qkv is not None)
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def _tiny_stablelm(use_qkv_bias=True):
    from transformers import StableLmConfig, StableLmForCausalLM
    config = StableLmConfig(vocab_size=96, hidden_size=32,
                            num_hidden_layers=2, num_attention_heads=2,
                            num_key_value_heads=1, intermediate_size=64,
                            partial_rotary_factor=0.5,
                            max_position_embeddings=64,
                            use_qkv_bias=use_qkv_bias,
                            attention_dropout=0.0,
                            tie_word_embeddings=False)
    torch.manual_seed(0)
    return config, StableLmForCausalLM(config).eval()


# partial-rotary + qkv-bias import seams stay fast via the phi3/qwen3 tests
@pytest.mark.slow
@pytest.mark.parametrize("use_qkv_bias", [True, False])
def test_stablelm_import_logit_parity_and_generate(workdir, use_qkv_bias):
    """StableLM: llama-shaped blocks with LayerNorm (weight+bias) norms,
    partial rotary, qkv bias on and off (the DSL bias flag is config-
    driven while the mapper keys off presence — both must stay in sync);
    cached greedy == uncached rollout."""
    config, torch_model = _tiny_stablelm(use_qkv_bias=use_qkv_bias)
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model,
                          f"stablelm-{'b' if use_qkv_bias else 'nb'}")
    assert model.status["code"] == "Imported"
    assert any(k.endswith("attn_block.0.bias") for k in model.params)
    assert any(k.endswith("attn_block.1.bias")
               for k in model.params) == use_qkv_bias
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def test_stablelm_variant_rejections():
    from transformers import StableLmConfig
    from penroz_tpu.models.dsl import Mapper
    par = StableLmConfig(vocab_size=96, hidden_size=32, num_hidden_layers=1,
                         num_attention_heads=2, use_parallel_residual=True)
    with pytest.raises(ValueError, match="use_parallel_residual"):
        Mapper.from_hf_config(par)
    qk = StableLmConfig(vocab_size=96, hidden_size=32, num_hidden_layers=1,
                        num_attention_heads=2, qk_layernorm=True)
    with pytest.raises(ValueError, match="qk_layernorm"):
        Mapper.from_hf_config(qk)


def _tiny_gptj():
    from transformers import GPTJConfig, GPTJForCausalLM
    config = GPTJConfig(vocab_size=96, n_positions=64, n_embd=32, n_layer=2,
                        n_head=2, rotary_dim=8, n_inner=None,
                        activation_function="gelu_new", resid_pdrop=0.0,
                        embd_pdrop=0.0, attn_pdrop=0.0,
                        tie_word_embeddings=False)
    torch.manual_seed(0)
    return config, GPTJForCausalLM(config).eval()


# parallel-residual rotary import stays fast via the NeoX cached-generate test
@pytest.mark.slow
def test_gptj_import_logit_parity_and_generate(workdir):
    """GPT-J: parallel branches sharing one ln_1, bias-free projections,
    biased head, and partial INTERLEAVED rotary — handled entirely at
    import by de-interleaving each head's q/k rows into the half-split
    layout (q·k dot products are permutation-invariant, so no runtime
    rope variant exists); cached greedy == uncached rollout."""
    config, torch_model = _tiny_gptj()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "gptj-tiny")
    assert model.status["code"] == "Imported"
    assert "summation" in str(model.layers_dsl)
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def _tiny_falcon(new_arch=False):
    from transformers import FalconConfig, FalconForCausalLM
    kwargs = dict(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                  num_attention_heads=2, bias=False, alibi=False,
                  attention_dropout=0.0, hidden_dropout=0.0,
                  max_position_embeddings=64, tie_word_embeddings=True)
    if new_arch:
        kwargs.update(new_decoder_architecture=True, num_kv_heads=1)
    else:
        kwargs.update(multi_query=True, parallel_attn=True,
                      new_decoder_architecture=False)
    config = FalconConfig(**kwargs)
    torch.manual_seed(0)
    return config, FalconForCausalLM(config).eval()


@pytest.mark.parametrize("new_arch", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_falcon_import_logit_parity_and_generate(workdir, new_arch):
    """Falcon, both decoder architectures: 7B-style MQA with one shared
    input_layernorm feeding parallel branches, and 40B-style GQA with
    separate ln_attn/ln_mlp (NeoX parallelresidual); fused
    query_key_value de-fused per architecture; tied head."""
    config, torch_model = _tiny_falcon(new_arch=new_arch)
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    tag = "falcon-new" if new_arch else "falcon-7b"
    model = _import_model(workdir, config, torch_model, tag)
    assert model.status["code"] == "Imported"
    dsl_s = str(model.layers_dsl)
    assert ("parallelresidual" in dsl_s) == new_arch
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def test_falcon_variant_rejections():
    from transformers import FalconConfig
    from penroz_tpu.models.dsl import Mapper
    ali = FalconConfig(vocab_size=96, hidden_size=32, num_hidden_layers=1,
                       num_attention_heads=2, alibi=True)
    with pytest.raises(ValueError, match="alibi"):
        Mapper.from_hf_config(ali)
    seqv = FalconConfig(vocab_size=96, hidden_size=32, num_hidden_layers=1,
                        num_attention_heads=2, parallel_attn=False,
                        new_decoder_architecture=False)
    with pytest.raises(ValueError, match="parallel_attn"):
        Mapper.from_hf_config(seqv)


def _tiny_bigcode(multi_query=True):
    from transformers import GPTBigCodeConfig, GPTBigCodeForCausalLM
    config = GPTBigCodeConfig(vocab_size=96, n_positions=64, n_embd=32,
                              n_layer=2, n_head=2, multi_query=multi_query,
                              activation_function="gelu_pytorch_tanh",
                              attn_pdrop=0.0, resid_pdrop=0.0,
                              embd_pdrop=0.0, tie_word_embeddings=True)
    torch.manual_seed(0)
    return config, GPTBigCodeForCausalLM(config).eval()


# multi-query import seam stays fast via the old-arch Falcon test
@pytest.mark.slow
@pytest.mark.parametrize("multi_query", [True, False])
def test_bigcode_import_logit_parity_and_generate(workdir, multi_query):
    """GPT-BigCode (StarCoder): the GPT-2 structure with multi-query
    attention — the MQA-fused c_attn is already our [q; k; v] layout —
    and plain nn.Linear weights (no Conv1D transpose); tied head."""
    config, torch_model = _tiny_bigcode(multi_query=multi_query)
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    tag = f"bigcode-{'mq' if multi_query else 'mh'}"
    model = _import_model(workdir, config, torch_model, tag)
    assert model.status["code"] == "Imported"
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    # 0.05: tight enough to catch a scrambled per-head QKV layout (the
    # multi_query=False mis-interleave measured ~0.075 at this scale)
    # while covering bf16 checkpoint noise (~0.002 when correct)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.05)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def _tiny_phi3(partial_rotary_factor=1.0):
    from transformers import Phi3Config, Phi3ForCausalLM
    config = Phi3Config(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                        num_attention_heads=2, num_key_value_heads=1,
                        intermediate_size=64, max_position_embeddings=64,
                        rope_theta=10000.0, attention_dropout=0.0,
                        partial_rotary_factor=partial_rotary_factor,
                        pad_token_id=0,  # default 32000 >= tiny vocab
                        tie_word_embeddings=False)
    torch.manual_seed(0)
    return config, Phi3ForCausalLM(config).eval()


# slow lane (tier1_budget): phi (shared-norm parallel branches) and neox
# (partial rotary) keep the family's import seams fast
@pytest.mark.slow
@pytest.mark.parametrize("partial_rotary_factor", [pytest.param(1.0, marks=pytest.mark.slow), 0.5])
def test_phi3_import_logit_parity_and_generate(workdir,
                                               partial_rotary_factor):
    """Phi-3: llama block structure with PRE-FUSED projections — qkv_proj
    already in our [q; k; v] layout, gate_up_proj split in half onto
    gate/up; GQA, RMSNorm, silu.  partial_rotary_factor<1 (the Phi-4-mini
    config shape) must rotate only that fraction of each head's dims."""
    config, torch_model = _tiny_phi3(partial_rotary_factor)
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    tag = f"phi3-r{int(partial_rotary_factor * 100)}"
    model = _import_model(workdir, config, torch_model, tag)
    assert model.status["code"] == "Imported"
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.05)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def _tiny_opt(enable_bias=True):
    from transformers import OPTConfig, OPTForCausalLM
    config = OPTConfig(vocab_size=96, hidden_size=32, num_hidden_layers=2,
                       num_attention_heads=2, ffn_dim=64,
                       max_position_embeddings=64, do_layer_norm_before=True,
                       word_embed_proj_dim=32, enable_bias=enable_bias,
                       activation_function="relu", dropout=0.0,
                       attention_dropout=0.0, layerdrop=0.0)
    torch.manual_seed(11)
    return config, OPTForCausalLM(config).eval()


# learned-positional import seam stays fast via the GPT-2 import test
@pytest.mark.slow
def test_opt_import_logit_parity_and_generate(workdir):
    """OPT: model.decoder layout, separate-then-fused biased QKV, ReLU
    MLPs, and the LEARNED position table's +2 row offset folded away at
    import (table[2:] == 0-based lookups under full attention masks) —
    cached greedy must equal the uncached rollout (positions ride the
    cache-length offset)."""
    config, torch_model = _tiny_opt()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "opt-tiny")
    assert model.status["code"] == "Imported"
    # position table lost its 2 offset rows
    assert model.params["layers.0.1.weight"].shape[0] == 64
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def test_opt_unsupported_variants_refused(workdir):
    """OPT-350m's post-norm ordering and embed projections must refuse
    loudly instead of importing wrong logits."""
    from penroz_tpu.models.dsl import Mapper
    from types import SimpleNamespace
    base = dict(model_type="opt", hidden_size=32, num_hidden_layers=1,
                num_attention_heads=2, vocab_size=96, ffn_dim=64,
                max_position_embeddings=64)
    with pytest.raises(ValueError, match="do_layer_norm_before"):
        Mapper.from_hf_config(SimpleNamespace(**base,
                                              do_layer_norm_before=False))
    with pytest.raises(ValueError, match="word_embed_proj_dim"):
        Mapper.from_hf_config(SimpleNamespace(**base,
                                              do_layer_norm_before=True,
                                              word_embed_proj_dim=16))


def _tiny_bloom():
    from transformers import BloomConfig, BloomForCausalLM
    config = BloomConfig(vocab_size=96, hidden_size=32, n_layer=2,
                         n_head=4, hidden_dropout=0.0,
                         attention_dropout=0.0)
    torch.manual_seed(13)
    return config, BloomForCausalLM(config).eval()


# alibi import seam stays fast via the Falcon-RW alibi test
@pytest.mark.slow
def test_bloom_import_logit_parity_and_generate(workdir):
    """BLOOM: no positional embedding at all — ALiBi logit biases carry
    position — plus the embedding LayerNorm and the per-head-interleaved
    fused QKV de-interleaved at import.  Cached greedy must equal the
    uncached rollout (the bias rides the cache positions)."""
    config, torch_model = _tiny_bloom()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "bloom-tiny")
    assert model.status["code"] == "Imported"
    # bare embedding + embedding-LayerNorm — no position table exists
    assert "layers.0.weight" in model.params
    assert model.params["layers.1.weight"].ndim == 1
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def test_bloom_configless_import_refused_with_named_field():
    """Config-less BLOOM state-dict mapping needs n_head for the per-head
    fused-QKV de-interleave: the key sniff (word_embeddings_layernorm)
    dispatches fine without a config, so the refusal must be a descriptive
    ValueError naming the missing field — not the bare AttributeError a
    ``getattr(None, 'n_head')`` would die with later (mirrors the GPT-2
    Conv1D-sniff refusal convention)."""
    import numpy as np
    from penroz_tpu.models.dsl import Mapper
    sd = {"transformer.word_embeddings_layernorm.weight": np.ones(8),
          "transformer.word_embeddings.weight": np.ones((16, 8))}
    with pytest.raises(ValueError, match="n_head"):
        Mapper.map_hf_state_dict_to_custom(sd, 1, config=None)


def test_bloom_post_layernorm_residual_refused():
    from penroz_tpu.models.dsl import Mapper
    from types import SimpleNamespace
    cfg = SimpleNamespace(model_type="bloom", hidden_size=32, n_layer=1,
                          n_head=4, vocab_size=96,
                          apply_residual_connection_post_layernorm=True)
    with pytest.raises(ValueError, match="post_layernorm"):
        Mapper.from_hf_config(cfg)


def test_opt_dropout_knobs_wired_separately():
    """attention_dropout drives the attention probs; `dropout` the
    embedding and both residual streams (opt-125m ships 0.1/0.0 — wiring
    them together silently diverges fine-tuning from HF)."""
    from penroz_tpu.models.dsl import Mapper
    from types import SimpleNamespace
    cfg = SimpleNamespace(model_type="opt", hidden_size=32,
                          num_hidden_layers=1, num_attention_heads=2,
                          vocab_size=96, ffn_dim=64,
                          max_position_embeddings=64,
                          do_layer_norm_before=True, word_embed_proj_dim=32,
                          enable_bias=True, activation_function="relu",
                          dropout=0.1, attention_dropout=0.0)
    layers = Mapper.from_hf_config(cfg)
    blk = layers[2]["residual"]
    attn_entry = blk[0]["sequential"][2]["attention"]
    assert attn_entry["dropout"] == 0.0
    assert blk[0]["sequential"][-1] == {"dropout": {"p": 0.1}}
    assert blk[1]["sequential"][-1] == {"dropout": {"p": 0.1}}
    assert layers[1] == {"dropout": {"p": 0.1}}


def _tiny_mpt(clip_qkv=None):
    from transformers import MptConfig, MptForCausalLM
    config = MptConfig(d_model=32, n_heads=4, n_layers=2, vocab_size=96,
                       expansion_ratio=4,
                       attn_config={"alibi": True, "clip_qkv": clip_qkv,
                                    "attn_pdrop": 0.0})
    torch.manual_seed(17)
    return config, MptForCausalLM(config).eval()


# slow lane (tier1_budget): falcon-rw keeps ALiBi import parity fast
@pytest.mark.slow
@pytest.mark.parametrize("clip_qkv", [None, pytest.param(4.0, marks=pytest.mark.slow)])
def test_mpt_import_logit_parity_and_generate(workdir, clip_qkv):
    """MPT: ALiBi (MPT's slope·(k−T+1) absolute form is softmax-shift-
    equivalent to our slope·(k−q)), weight-only LayerNorms, bias-free
    projections, Wqkv already in our fused layout, optional clip_qkv
    clamp shifting the branch indices."""
    config, torch_model = _tiny_mpt(clip_qkv=clip_qkv)
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    tag = "mpt-clip" if clip_qkv else "mpt-tiny"
    model = _import_model(workdir, config, torch_model, tag)
    assert model.status["code"] == "Imported"
    assert not any(k.endswith(".bias") for k in model.params)  # no_bias
    import json as _json
    assert ('"clamp"' in _json.dumps(model.layers_dsl)) == \
        (clip_qkv is not None)
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def test_mpt_unsupported_variants_refused():
    from penroz_tpu.models.dsl import Mapper
    from types import SimpleNamespace
    base = dict(model_type="mpt", d_model=32, n_layers=1, vocab_size=96)
    with pytest.raises(ValueError, match="alibi"):
        Mapper.from_hf_config(SimpleNamespace(
            **base, n_heads=4, attn_config={"alibi": False}))
    with pytest.raises(ValueError, match="power-of-two"):
        Mapper.from_hf_config(SimpleNamespace(
            **base, n_heads=6, attn_config={"alibi": True}))
    with pytest.raises(ValueError, match="qk_ln"):
        Mapper.from_hf_config(SimpleNamespace(
            **base, n_heads=4, attn_config={"alibi": True, "qk_ln": True}))


def _tiny_qwen2_moe(norm_topk=False):
    from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM
    config = Qwen2MoeConfig(vocab_size=96, hidden_size=32,
                            num_hidden_layers=2, num_attention_heads=4,
                            num_key_value_heads=2, intermediate_size=64,
                            moe_intermediate_size=48,
                            shared_expert_intermediate_size=80,
                            num_experts=4, num_experts_per_tok=2,
                            norm_topk_prob=norm_topk,
                            decoder_sparse_step=1, mlp_only_layers=[],
                            max_position_embeddings=64,
                            attention_dropout=0.0)
    torch.manual_seed(19)
    return config, Qwen2MoeForCausalLM(config).eval()


# MoE import seam stays fast via the Mixtral test
@pytest.mark.slow
@pytest.mark.parametrize("norm_topk", [False, True])
def test_qwen2_moe_import_logit_parity_and_generate(workdir, norm_topk):
    """Qwen2-MoE: fine-grained routed experts (norm_topk_prob both ways —
    the default False keeps raw softmax mass on the selected experts)
    plus the always-on shared expert behind a sigmoid token gate; qwen2
    qkv biases; cached greedy == uncached rollout."""
    config, torch_model = _tiny_qwen2_moe(norm_topk=norm_topk)
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    tag = f"q2moe-{'n' if norm_topk else 'r'}"
    model = _import_model(workdir, config, torch_model, tag)
    assert model.status["code"] == "Imported"
    assert any("shared_expert_gate" in k for k in model.params)
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


def test_qwen2_moe_sparse_step_refused():
    from penroz_tpu.models.dsl import Mapper
    config, _ = _tiny_qwen2_moe()
    config.decoder_sparse_step = 2
    with pytest.raises(ValueError, match="decoder_sparse_step"):
        Mapper.from_hf_config(config)


def test_gemma2_softcapping_and_query_scale_parity(workdir):
    """Gemma-2's attn/final logit soft-capping and query_pre_attn_scalar
    scaling — set AGGRESSIVELY here (caps ~ logit magnitude, scalar far
    from head_dim) so the nonlinearity and the scale actually bite: a
    build that drops either would fail this parity while passing the
    neutralized `_tiny_gemma2` test."""
    from transformers import Gemma2Config, Gemma2ForCausalLM
    config = Gemma2Config(vocab_size=96, hidden_size=16, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          head_dim=8, intermediate_size=32,
                          max_position_embeddings=64, rope_theta=10000.0,
                          attn_logit_softcapping=2.0,
                          final_logit_softcapping=1.5,
                          query_pre_attn_scalar=64, sliding_window=64,
                          attention_dropout=0.0,
                          hidden_activation="gelu_pytorch_tanh")
    torch.manual_seed(5)
    torch_model = Gemma2ForCausalLM(config).eval()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "gemma2-cap")
    assert model.status["code"] == "Imported"
    import json as _json
    assert '"softcap"' in _json.dumps(model.layers_dsl)
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    # capped logits are small and bounded — compare directly, no centering
    np.testing.assert_allclose(ours, ref_logits, atol=0.02)

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)


# slow lane (tier1_budget): gemma2 parity + softcap/query-scale + sliding
# layers stay fast as the family's architectural twin
@pytest.mark.slow
def test_gemma3_import_logit_parity_and_generate(workdir):
    """Gemma-3: per-head q/k RMS norms (zero-centered weights, +1 at
    import), rope_local_base_freq on sliding layers, LINEAR rope scaling
    on global layers, query_pre_attn_scalar scaling, sandwich norms —
    every field set to a value that would show if dropped."""
    from transformers import Gemma3TextConfig, Gemma3ForCausalLM
    config = Gemma3TextConfig(
        vocab_size=96, hidden_size=16, num_hidden_layers=2,
        num_attention_heads=2, num_key_value_heads=1, head_dim=8,
        intermediate_size=32, max_position_embeddings=64,
        rope_theta=1_000_000.0, rope_local_base_freq=10_000.0,
        rope_scaling={"rope_type": "linear", "factor": 8.0},
        layer_types=["sliding_attention", "full_attention"],
        sliding_window=16, query_pre_attn_scalar=64,
        attention_dropout=0.0, hidden_activation="gelu_pytorch_tanh")
    torch.manual_seed(7)
    torch_model = Gemma3ForCausalLM(config).eval()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "gemma3-tiny")
    assert model.status["code"] == "Imported"
    assert any(k.endswith("q_norm.weight") for k in model.params)
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=32,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6, block=32)


def test_falcon_rw_alibi_import_logit_parity_and_generate(workdir):
    """falcon-rw (RefinedWeb): ALiBi + sequential pre-LN blocks + the
    BLOOM-style per-head-interleaved fused QKV — previously refused,
    supported since ALiBi attention landed.  Other alibi combos keep the
    loud refusal."""
    from transformers import FalconConfig, FalconForCausalLM
    config = FalconConfig(vocab_size=96, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          alibi=True, multi_query=False,
                          parallel_attn=False,
                          new_decoder_architecture=False, bias=True,
                          attention_dropout=0.0, hidden_dropout=0.0)
    torch.manual_seed(21)
    torch_model = FalconForCausalLM(config).eval()
    tokens = np.array([[3, 17, 42, 8, 11]], np.int64)
    with torch.no_grad():
        ref_logits = torch_model(torch.tensor(tokens)).logits.float().numpy()

    model = _import_model(workdir, config, torch_model, "falcon-rw")
    assert model.status["code"] == "Imported"
    import jax.numpy as jnp
    acts, _, _, _ = model.arch.jit_forward(model.params, model.buffers,
                                           jnp.asarray(tokens, jnp.int32),
                                           skip_softmax=True)
    ours = np.asarray(acts[-1], np.float32)
    ref_c = ref_logits - ref_logits.mean(-1, keepdims=True)
    ours_c = ours - ours.mean(-1, keepdims=True)
    np.testing.assert_allclose(ours_c, ref_c, atol=0.15)
    assert (ours.argmax(-1) == ref_logits.argmax(-1)).mean() >= 0.8

    toks = model.generate_tokens([[1, 2, 3]], block_size=16,
                                 max_new_tokens=6, temperature=0.0)
    assert toks == _greedy_rollout(model, [1, 2, 3], 6)

    # non-rw alibi combos stay refused
    from penroz_tpu.models.dsl import Mapper
    bad = FalconConfig(vocab_size=96, hidden_size=32, num_hidden_layers=1,
                       num_attention_heads=4, alibi=True, multi_query=True)
    with pytest.raises(ValueError, match="falcon-rw"):
        Mapper.from_hf_config(bad)
