"""Sharded (multi-host TP/SP/EP) checkpointing.

Cross-host-sharded jax.Arrays cannot exist in a single-process test, so the
non-addressable side is exercised through fake shard-carrying arrays — the
same seam the reference uses for distributed tests (SURVEY.md §4: mock the
launcher, test the math).  Reassembly, shard-file lifecycle, and the
optimizer-state sharding tree run for real.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel
from penroz_tpu.parallel import dist
from penroz_tpu.parallel import mesh as mesh_lib
from penroz_tpu.parallel import sharding as sharding_lib
from penroz_tpu.utils import checkpoint

# CI tier: heavier compiles (see pyproject markers / ci.yml shards).
pytestmark = pytest.mark.runtime


@dataclasses.dataclass
class _FakeShard:
    index: tuple
    data: np.ndarray
    replica_id: int = 0


class _FakeShardedArray:
    """Stands in for a cross-host-sharded jax.Array: not addressable, not
    replicated; exposes only this 'host's shards."""

    is_fully_addressable = False
    is_fully_replicated = False

    def __init__(self, full: np.ndarray, row_range: tuple):
        self.shape = full.shape
        self.dtype = full.dtype
        lo, hi = row_range
        self.addressable_shards = [_FakeShard(
            index=(slice(lo, hi), slice(0, full.shape[1])),
            data=full[lo:hi])]


_LAYERS = [{"linear": {"in_features": 8, "out_features": 4}}]
_OPT = {"adamw": {"lr": 1e-3, "betas": [0.9, 0.95], "eps": 1e-8}}


def _make_model(model_id="shardy"):
    return NeuralNetworkModel(model_id, Mapper(_LAYERS, _OPT))


def test_sharded_round_trip(workdir, monkeypatch):
    """Two 'hosts' each persist their half of a sharded param; deserialize
    reassembles the full array from the blob + shard files."""
    model = _make_model()
    full = np.arange(32, dtype=np.float32).reshape(4, 8)
    key = "layers.0.weight"
    assert key in model.params

    monkeypatch.setattr(dist, "process_count", lambda: 2)
    for rank, rows in ((1, (2, 4)), (0, (0, 2))):  # master saves last
        monkeypatch.setattr(dist, "process_index", lambda r=rank: r)
        model.params = dict(model.params)
        model.params[key] = _FakeShardedArray(full, rows)
        model.serialize(sync_flush=True, tag=0)

    blob = checkpoint.load("shardy")
    assert key not in blob["params"]
    # the non-pickle container JSON-ifies tuples to lists
    assert tuple(blob["sharded"][key]["shape"]) == (4, 8)
    assert len(checkpoint.load_shards("shardy")) == 2

    restored = NeuralNetworkModel.deserialize("shardy")
    np.testing.assert_array_equal(np.asarray(restored.params[key]), full)
    # bias was a normal addressable array → lives in the blob as usual
    assert "layers.0.bias" in blob["params"]


def test_sharded_opt_state_round_trip(workdir, monkeypatch):
    """Sharded optimizer leaves persist via __opt__ names and reassemble."""
    model = _make_model("shardopt")
    leaves = jax.tree.leaves(model.opt_state)
    mu_idx = next(i for i, l in enumerate(leaves)
                  if tuple(getattr(l, "shape", ())) == (4, 8))
    full = np.full((4, 8), 7.0, np.float32)

    def fake_leaves():
        new = [np.asarray(l) for l in leaves]
        return new

    monkeypatch.setattr(dist, "process_count", lambda: 2)
    for rank, rows in ((1, (2, 4)), (0, (0, 2))):
        monkeypatch.setattr(dist, "process_index", lambda r=rank: r)
        new_leaves = fake_leaves()
        new_leaves[mu_idx] = _FakeShardedArray(full, rows)
        model.opt_state = jax.tree.unflatten(
            jax.tree.structure(model.opt_state), new_leaves)
        model.serialize(sync_flush=True, tag=0)

    restored = NeuralNetworkModel.deserialize("shardopt")
    got = jax.tree.leaves(restored.opt_state)[mu_idx]
    np.testing.assert_array_equal(np.asarray(got), full)


def test_incomplete_shards_raise(workdir, monkeypatch):
    """Missing a host's shard file → loud RuntimeError, not silent zeros."""
    model = _make_model("partial")
    full = np.ones((4, 8), np.float32)
    monkeypatch.setattr(dist, "process_index", lambda: 0)
    model.params = dict(model.params)
    model.params["layers.0.weight"] = _FakeShardedArray(full, (0, 2))
    model.serialize(sync_flush=True, tag=0)  # rank 1's file never written
    with pytest.raises(RuntimeError, match="incomplete"):
        NeuralNetworkModel.deserialize("partial")


def test_delete_removes_shard_files(workdir, monkeypatch):
    model = _make_model("deleteme")
    monkeypatch.setattr(dist, "process_index", lambda: 0)
    model.params = dict(model.params)
    model.params["layers.0.weight"] = _FakeShardedArray(
        np.ones((4, 8), np.float32), (0, 4))
    model.serialize(sync_flush=True, tag=0)
    assert len(checkpoint.load_shards("deleteme")) == 1
    NeuralNetworkModel.delete("deleteme")
    assert checkpoint.load_shards("deleteme") == []
    with pytest.raises(KeyError):
        NeuralNetworkModel.deserialize("deleteme")


def test_opt_state_sharding_follows_params(cpu_devices):
    """AdamW mu/nu inherit the param TP layout; counts stay replicated."""
    import optax
    mesh = mesh_lib.make_mesh(cpu_devices, model=2)
    params = {"blk.qkv.weight": jnp.zeros((96, 32)),
              "blk.qkv.bias": jnp.zeros((96,))}
    opt_state = optax.adamw(1e-3).init(params)
    tree = sharding_lib.opt_state_sharding_tree(opt_state, params, mesh)
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    by_path = {jax.tree_util.keystr(path): s for path, s in flat}
    mu_w = next(s for p, s in by_path.items()
                if "mu" in p and "qkv.weight" in p)
    assert mu_w.spec == sharding_lib.P(sharding_lib.MODEL_AXIS, None)
    counts = [s for p, s in by_path.items() if "count" in p]
    assert all(s.spec == sharding_lib.P() for s in counts)


def test_multihost_mesh_allows_tensor_parallel(workdir, monkeypatch,
                                               cpu_devices):
    """PENROZ_MESH_MODEL under a (mocked) 2-process world now builds a TP
    mesh instead of being ignored (round-1 restriction lifted)."""
    model = _make_model("tpmesh")
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    monkeypatch.setenv("PENROZ_MESH_MODEL", "2")
    mesh = model._multihost_mesh(micro_batch=8)
    assert mesh.shape[mesh_lib.MODEL_AXIS] == 2
    assert mesh.shape[mesh_lib.DATA_AXIS] == len(cpu_devices) // 2

    monkeypatch.setenv("PENROZ_MESH_MODEL", "3")  # 8 % 3 != 0
    with pytest.raises(ValueError, match="not divisible"):
        model._multihost_mesh(micro_batch=8)


def test_pipe_layout_error_path_stays_one_sided_safe(workdir, monkeypatch):
    """Error-path cleanup under multi-host pipe: unstacking cross-host
    stacked leaves is a collective, so a host arriving alone must keep the
    stacked layout (local_only) and an untagged serialize must degrade to
    master metadata BEFORE attempting the canonical conversion."""
    model = _make_model("pipeerr")
    model.serialize(sync_flush=True)  # a blob for the meta-only update
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    full = np.arange(32, dtype=np.float32).reshape(4, 8)
    model.params = dict(model.params)
    model.params["__pipe__.mlp.weight"] = _FakeShardedArray(full, (0, 2))
    model._pipe_layout = (0, 4)

    model._exit_pipe_layout(local_only=True)
    assert model._pipe_layout == (0, 4)  # layout kept, no collective

    # untagged save: meta-only path, never touches _canonical_state (which
    # would raise on the fake array's missing __getitem__)
    model.status = {"code": "Error", "message": "boom"}
    model.serialize(sync_flush=True)
    restored = NeuralNetworkModel.deserialize("pipeerr")
    assert restored.status["code"] == "Error"


def test_multihost_mesh_pipe_axis(workdir, monkeypatch, cpu_devices):
    """PENROZ_MESH_PIPE under a (mocked) 2-process world builds the pipe
    axis outermost: stage s occupies a contiguous global device range, so
    stages align with host groups and the handoff rides DCN."""
    model = _make_model("pipemesh")
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    mesh = model._multihost_mesh(micro_batch=8)
    assert mesh.shape[mesh_lib.PIPE_AXIS] == 2
    assert mesh.shape[mesh_lib.DATA_AXIS] == len(cpu_devices) // 2
    # outermost: the first half of jax.devices() is stage 0, second stage 1
    devs = mesh.devices  # (data, model, seq, expert, pipe)
    n = len(cpu_devices)
    stage0 = {d.id for d in devs[..., 0].ravel()}
    stage1 = {d.id for d in devs[..., 1].ravel()}
    assert stage0 == {d.id for d in cpu_devices[: n // 2]}
    assert stage1 == {d.id for d in cpu_devices[n // 2:]}

    # forward-only callers fold pipe into data capacity
    folded = model._multihost_mesh(micro_batch=8, fold_pipe=True)
    assert folded.shape[mesh_lib.PIPE_AXIS] == 1
    assert folded.shape[mesh_lib.DATA_AXIS] == len(cpu_devices)

    # batch must divide the within-stage data axis
    with pytest.raises(ValueError, match="divisible by the data axis"):
        model._multihost_mesh(micro_batch=3)

    # stage/host misalignment refused (3 stages over 2 processes)
    monkeypatch.setenv("PENROZ_MESH_PIPE", "3")
    with pytest.raises(RuntimeError, match="align with host boundaries"):
        model._multihost_mesh(micro_batch=8)

    # seq composes with pipe (both SP modes) as of round 4; the mesh
    # builder no longer refuses it — nothing to assert here beyond shape
    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    monkeypatch.setenv("PENROZ_MESH_SEQUENCE", "2")
    m2 = model._multihost_mesh(micro_batch=8, block_size=16)
    assert m2.shape[mesh_lib.SEQ_AXIS] == 2


def test_master_prunes_stale_higher_rank_shards(workdir, monkeypatch):
    """Retraining with a smaller world must remove leftover shard files from
    the larger run, or reassembly would overwrite fresh weights with stale
    pieces."""
    full = np.ones((4, 8), np.float32)
    # Fake leftovers from an earlier 4-process run.
    for idx in (2, 3):
        checkpoint.save_shard("shrink", idx, {"tag": "old", "pieces": {}},
                              sync_flush=True)
    assert len(checkpoint.load_shards("shrink")) == 2

    model = _make_model("shrink")
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    for rank, rows in ((1, (2, 4)), (0, (0, 2))):
        monkeypatch.setattr(dist, "process_index", lambda r=rank: r)
        model.params = dict(model.params)
        model.params["layers.0.weight"] = _FakeShardedArray(full, rows)
        model.serialize(sync_flush=True, tag=5)

    shards = checkpoint.load_shards("shrink")
    assert len(shards) == 2  # stale shard2/shard3 pruned by the master
    assert all(p["tag"] == 5 for p in shards)
    restored = NeuralNetworkModel.deserialize("shrink")
    np.testing.assert_array_equal(
        np.asarray(restored.params["layers.0.weight"]), full)


def test_torn_checkpoint_tag_mismatch_raises(workdir, monkeypatch):
    """Shard files from a different step than the blob are rejected."""
    full = np.ones((4, 8), np.float32)
    model = _make_model("torn")
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    # rank 1 checkpoints at step 3; master then checkpoints at step 4
    monkeypatch.setattr(dist, "process_index", lambda: 1)
    model.params = dict(model.params)
    model.params["layers.0.weight"] = _FakeShardedArray(full, (2, 4))
    model.serialize(sync_flush=True, tag=3)
    monkeypatch.setattr(dist, "process_index", lambda: 0)
    model.params = dict(model.params)
    model.params["layers.0.weight"] = _FakeShardedArray(full, (0, 2))
    model.serialize(sync_flush=True, tag=4)
    with pytest.raises(RuntimeError, match="torn"):
        NeuralNetworkModel.deserialize("torn")


def test_untagged_serialize_on_sharded_params_is_meta_only(workdir,
                                                           monkeypatch):
    """An uncoordinated (untagged) serialize on a sharded model — error
    path, train-start status write, serve-side save — must not rewrite
    shard files or the blob's weight sections; it only updates metadata.
    One host rewriting its shard alone would permanently tear the last
    consistent checkpoint."""
    full = np.arange(32, dtype=np.float32).reshape(4, 8)
    key = "layers.0.weight"
    model = _make_model("metaonly")
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    for rank, rows in ((1, (2, 4)), (0, (0, 2))):
        monkeypatch.setattr(dist, "process_index", lambda r=rank: r)
        model.params = dict(model.params)
        model.params[key] = _FakeShardedArray(full, rows)
        model.serialize(sync_flush=True, tag=7)

    # Uncoordinated follow-up on the master (e.g. the error path).
    model.status = {"code": "Error", "message": "boom"}
    model.serialize(sync_flush=True)  # untagged

    blob = checkpoint.load("metaonly")
    assert blob["status"]["code"] == "Error"       # metadata updated
    assert blob["shard_tag"] == 7                  # weights untouched
    shards = checkpoint.load_shards("metaonly")
    assert [s["tag"] for s in shards] == [7, 7]
    restored = NeuralNetworkModel.deserialize("metaonly")
    np.testing.assert_array_equal(np.asarray(restored.params[key]), full)

    # Non-master untagged serialize is a complete no-op.
    monkeypatch.setattr(dist, "process_index", lambda: 1)
    model.status = {"code": "Training", "message": "x"}
    model.serialize(sync_flush=True)
    assert checkpoint.load("metaonly")["status"]["code"] == "Error"
