"""Native C++ BPE core tests: build, train/encode oracle equivalence,
round-trips, model files, facade integration."""

import pytest

from penroz_tpu.data import bpe as bpe_mod
from penroz_tpu.data.bpe import ByteBPE, _PyEncoder, _py_train, split_words

CORPUS = ("the quick brown fox jumps over the lazy dog. " * 50 +
          "she sells sea shells by the sea shore 987 " * 30)


@pytest.fixture(scope="module")
def trained():
    return ByteBPE.train_from_text(CORPUS, vocab_size=320)


def test_split_words_scheme():
    assert split_words(b"hi there") == [b"hi", b" there"]
    assert split_words(b"a1b") == [b"a", b"1", b"b"]
    assert split_words(b"x  y") == [b"x", b" ", b" y"]
    assert split_words(b"12 34") == [b"12", b" ", b"34"]
    assert split_words(b"") == []


def test_train_produces_merges(trained):
    assert trained.vocab_size > 256
    assert all(isinstance(m, tuple) and len(m) == 2 for m in trained.merges)


def test_roundtrip(trained):
    for text in ["the quick fox", "shells 987", "unseen wörds ok",
                 "punct!? (mix) 42"]:
        assert trained.decode(trained.encode(text)) == text


def test_compression(trained):
    text = "the quick brown fox jumps over the lazy dog"
    assert len(trained.encode(text)) < len(text.encode())


def test_native_matches_python_oracle(trained):
    if not trained.native:
        pytest.skip("native core unavailable")
    merges_py = _py_train(CORPUS.encode(), len(trained.merges))
    assert merges_py == trained.merges
    oracle = _PyEncoder(trained.merges)
    for text in [CORPUS[:200], "brand new input 123", "dog dog dog"]:
        assert oracle.encode(text.encode()) == trained.encode(text)


def test_save_load_roundtrip(trained, tmp_path):
    path = tmp_path / "model.json"
    trained.save(str(path))
    loaded = ByteBPE.load(str(path))
    assert loaded.merges == trained.merges
    text = "the lazy shore"
    assert loaded.encode(text) == trained.encode(text)


def test_load_rejects_bad_format(tmp_path):
    path = tmp_path / "bad.json"
    path.write_text('{"format": "other"}')
    with pytest.raises(ValueError):
        ByteBPE.load(str(path))


def test_tokenizer_facade(trained, tmp_path):
    from penroz_tpu.data.tokenizers import Tokenizer
    path = tmp_path / "model.json"
    trained.save(str(path))
    tok = Tokenizer(f"bpe:{path}")
    tokens = tok.tokenize("sea shells")
    assert tokens[-1] == trained.eot_token
    assert tok.decode(tokens) == "sea shells"


def test_python_fallback_when_native_missing(monkeypatch):
    monkeypatch.setattr(bpe_mod, "_load_native", lambda: None)
    bpe = ByteBPE.train_from_text("aaa bbb aaa bbb aaa", vocab_size=260)
    assert not bpe.native
    assert bpe.decode(bpe.encode("aaa bbb")) == "aaa bbb"
