"""Model presets: DSL validity + parameter counts of the GPT-2 ladder.

Counts follow the GPT-2 architecture formula (per block:
12*d^2 + 13*d; embeddings vocab*d + block*d; final ln 2d) — the same
arithmetic the reference's shape/param-count test tables pin for its DSL
(test_neural_net_model.py:19-104)."""

import pytest

from penroz_tpu.models import presets
from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import CompiledArch


def _expected(d, depth, vocab=50304, block=1024):
    per_block = 12 * d * d + 13 * d
    # + vocab*d twice: wte AND the untied lm_head linear — the DSL
    # instantiates a separate output projection exactly like the
    # reference's /model/ example (main.py:53-84); HF import overwrites it
    # with the tied weight (mappers.py:352)
    return 2 * vocab * d + block * d + depth * per_block + 2 * d


@pytest.mark.parametrize("size,d,depth", [
    ("gpt2", 768, 12),
    ("gpt2-medium", 1024, 24),
    ("gpt2-large", 1280, 36),
    ("gpt2-xl", 1600, 48),
])
def test_gpt2_param_counts(size, d, depth):
    layers = presets.gpt2(size)
    assert presets.param_count(layers) == _expected(d, depth)


def test_gpt2_124m_matches_reference_example_structure():
    """Same layer sequence as the reference's /model/ OpenAPI example
    (main.py:53-84): summation(embed+pos), dropout, 12 residual blocks,
    ln, lm_head, softmax."""
    layers = presets.gpt2("gpt2")
    assert "summation" in layers[0]
    assert "dropout" in layers[1]
    assert sum("residual" in l for l in layers) == 12
    assert "softmaxlast" in layers[-1]
    assert layers[-2]["linear"]["bias"] is False


def test_gpt2_xl_module_tree():
    """The 1.5B DSL compiles to a module tree (param_count above is
    allocation-free via eval_shape, so even xl count-checks cheaply)."""
    layers = presets.gpt2("gpt2-xl")
    arch = CompiledArch.get(Mapper(layers, presets.ADAMW).layers)
    assert sum("residual" in l for l in layers) == 48
    assert len(arch.attn_layers) == 48


def test_graft_entry_delegates_to_presets():
    """The driver contract's flagship DSL is the canonical builder's output
    — the two can never drift."""
    import __graft_entry__ as g
    assert g._gpt2_dsl() == presets.gpt2("gpt2")


def test_unknown_size_rejected():
    with pytest.raises(ValueError, match="unknown gpt2 size"):
        presets.gpt2("gpt5")


def test_makemore_mlp_trains(workdir, toy_shards):
    """BASELINE CPU-parity config: the char-MLP preset trains end-to-end
    single-process."""
    from penroz_tpu.models.model import NeuralNetworkModel
    model = NeuralNetworkModel(
        "mmlp", Mapper(presets.makemore_mlp(vocab=64),
                       {"sgd": {"lr": 0.1}}))
    model.train_model("toy", shard=0, epochs=2, batch_size=4,
                      block_size=8, step_size=2)
    assert model.status["code"] == "Trained"
