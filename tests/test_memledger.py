"""HBM capacity-ledger tests (serve/memledger.py): page-granularity
ownership attribution, the strict-mode leak sanitizer, and the engine
crash flight recorder.

The load-bearing invariants:

- **The partition holds everywhere** — every paged-pool page is in
  exactly one owner state and the states sum to pool capacity, across
  the full serving matrix (prefix cache × int8 KV × supersteps × spec
  decode × LoRA) and after every injected fault-site crash.  The whole
  suite runs with ``PENROZ_MEMLEDGER_STRICT=1`` (tests/conftest.py), so
  every retirement/preemption/crash-recovery seam re-proves it in the
  worker thread too — a leak anywhere fails the request, not just this
  file.
- **Attribution is honest** — ``GET /memory/`` per-tenant page counts
  are pinned against an INDEPENDENT walk of the device block table
  (assigned entries minus radix-aliased pages), not against the
  ledger's own arithmetic.
- **The flight recorder keeps the evidence** — ``GET /debug/dump``
  after an injected ``decode.step`` crash serves the PRE-crash ledger
  and tick timeline that ``_alloc_state`` then throws away.
"""

import asyncio
import json
import queue
import re
import time

import numpy as np
import pytest

from penroz_tpu.models import lora
from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel
from penroz_tpu.utils import faults

pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}
PAGE = 4
# Repetitive prompt: the 1-gram prompt-lookup matcher drafts early, so
# spec combos provably exercise the verify path.
REP_PROMPT = [1, 2, 3, 1, 2, 3, 1, 2]


@pytest.fixture(autouse=True)
def _ledger_state(workdir):
    """Fresh engine registry + every process-wide counter the ledger
    reads or feeds: fault ordinals, QoS quotas, KV drop/underflow
    globals, the adapter host cache, the serve-metrics registry, and the
    flight-recorder ring (process-wide — it survives
    decode_scheduler.reset() by design, so tests must drop it)."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import adapters, decode_scheduler, memledger, qos
    from penroz_tpu.serve import metrics as serve_metrics
    from penroz_tpu.utils import tracing

    def _zero():
        faults.reset()
        qos.reset()
        tracing.reset()
        serve_metrics.reset()
        KV.reset_pool_drop_count()
        KV.reset_unpin_underflow_count()
        adapters.REGISTRY.reset()
        memledger.reset()

    _zero()
    yield
    decode_scheduler.reset()
    _zero()


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("memgpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def make_engine():
    from penroz_tpu.serve import decode_scheduler
    engines = []

    def build(*args, **kwargs):
        engine = decode_scheduler.DecodeEngine(*args, **kwargs)
        engines.append(engine)
        return engine

    yield build
    for engine in engines:
        engine.shutdown()


@pytest.fixture
def paged_env(monkeypatch):
    """Paged pool + radix prefix cache + chunked prefill sized to the
    BLOCK=16 toy prompts (page = 4 tokens, cache region = 8 pages)."""
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", str(PAGE))
    monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "4")
    return monkeypatch


@pytest.fixture
def client(workdir):
    from penroz_tpu.serve import app as app_mod
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    from aiohttp.test_utils import TestClient, TestServer
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield client, loop
    loop.run_until_complete(client.close())
    loop.close()


def _request(client_loop, method, path, **kw):
    client, loop = client_loop

    async def go():
        resp = await client.request(method, path, **kw)
        body = await resp.read()
        return resp, body

    return loop.run_until_complete(go())


def _json(client_loop, method, path, **kw):
    resp, body = _request(client_loop, method, path, **kw)
    return resp.status, (json.loads(body) if body else None)


class _Collector:
    def __init__(self, prompt):
        self.q = queue.Queue()
        self.tokens = list(prompt)

    def on_event(self, kind, value):
        self.q.put((kind, value))

    def result(self, timeout=180):
        deadline = time.monotonic() + timeout
        while True:
            kind, value = self.q.get(
                timeout=max(deadline - time.monotonic(), 0.1))
            if kind == "token":
                self.tokens.append(value)
            elif kind == "done":
                return self.tokens
            else:
                raise value


def _submit(engine, prompt, max_new, tenant=None, adapter=None):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt)
    engine.submit(decode_scheduler.Request(prompt, max_new, None,
                                           collector.on_event,
                                           tenant=tenant, adapter=adapter))
    return collector


def _settle(engine, timeout=30):
    """Wait for the tick that retired the last request to finish (the
    'done' event ships from inside the emit loop, before the tick's
    retirement bookkeeping runs)."""
    deadline = time.monotonic() + timeout
    stats = engine.stats()
    while time.monotonic() < deadline:
        time.sleep(0.05)
        nxt = engine.stats()
        if (engine.idle()
                and nxt["decode_tokens"] == stats["decode_tokens"]
                and len(nxt["tick_timeline"]) == len(stats["tick_timeline"])):
            return
        stats = nxt


def _block_table_walk(engine):
    """INDEPENDENT per-tenant page attribution: walk the device block
    table counting assigned physical pages within each live row's valid
    length, minus the radix-cache pages the row merely aliases.  Shares
    no arithmetic with MemoryLedger._snapshot_locked (set difference on
    physical page ids vs. ceil-division on counts) — caller holds
    ``engine._cond``."""
    kv = engine._kv
    page = kv.page_size
    table = np.asarray(kv.block_table)
    row_pages, tenants = 0, {}
    for i, row in enumerate(engine._rows):
        if row is None:
            continue
        used = -(-int(engine._lengths[i]) // page)
        assigned = {int(p) for p in table[i, :used].tolist() if int(p) >= 0}
        aliased = {nd.page for nd in row.prefix_nodes}
        owned = len(assigned - aliased)
        row_pages += owned
        t = row.req.tenant
        tenants[t] = tenants.get(t, 0) + owned
    return row_pages, tenants


def _oracle_drafter(bases):
    """Draft the exact greedy continuation so the verify path provably
    engages (full acceptance, multi-token emission)."""
    def propose(history, k, n):
        for base in bases:
            if len(history) < len(base) and history == base[:len(history)]:
                return [int(t) for t in base[len(history):len(history) + k]]
        return []
    return propose


# ---------------------------------------------------------------------------
# THE invariant matrix: partition + parity across every serving variant
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("paged,int8,superstep,spec,use_lora,mesh", [
    # contiguous-cache ledger accounting is covered by the contig arms
    # of the scheduler parity matrices
    pytest.param(0, 0, 1, 0, 0, 0, marks=pytest.mark.slow),
    (1, 0, 1, 0, 0, 0),
    # int8 step-1 ledger accounting is covered by int8-superstep8
    pytest.param(1, 1, 1, 0, 0, 0, marks=pytest.mark.slow),
    # superstep retirement seams covered at step 8
    pytest.param(1, 0, 4, 0, 0, 0, marks=pytest.mark.slow),
    (1, 1, 8, 0, 0, 0), (1, 0, 1, 1, 0, 0),
    # lora/mesh attribution covered by the lora-serving crash-recovery
    # and mixed-tenant attribution tests (tier1_budget slow lane)
    pytest.param(1, 0, 1, 0, 1, 0, marks=pytest.mark.slow),
    pytest.param(1, 0, 1, 0, 0, 1, marks=pytest.mark.slow)],
    ids=["fp-contig", "paged-prefix", "int8-paged-prefix", "superstep4",
         "int8-superstep8", "spec-paged-prefix", "lora-paged-prefix",
         "mesh-paged-prefix"])
def test_ledger_invariant_parity_matrix(gpt_model, make_engine, monkeypatch,
                                        paged, int8, superstep, spec,
                                        use_lora, mesh):
    """Across prefix cache × int8 KV × supersteps × spec decode × LoRA:
    greedy outputs stay token-identical to the standalone path (the
    ledger observes, never steers), every page lands in exactly one
    owner state, the states sum to pool capacity, and an explicit final
    audit finds nothing — with strict mode having already re-proved the
    invariant at every retirement seam inside the worker."""
    from penroz_tpu.serve import adapters, spec_decode
    if paged:
        monkeypatch.setenv("PAGED_KV_CACHE", "1")
        monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", str(PAGE))
        monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
        monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "4")
    if int8:
        monkeypatch.setenv("TURBO_QUANT_KV_CACHE", "1")
    if mesh:
        # 1-device serving mesh: byte attribution must stay identical to
        # the unsharded engine (shard_shape is the identity there).
        monkeypatch.setenv("PENROZ_SERVE_MESH", "1")
        monkeypatch.setenv("PENROZ_SERVE_MESH_MODEL", "1")
    if superstep > 1:
        from penroz_tpu.serve import decode_scheduler
        monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, str(superstep))
    pa, pb = list(REP_PROMPT), [5, 6, 7]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 6, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 8, temperature=0.0)
    if spec:
        monkeypatch.setenv("PENROZ_SPEC_DECODE", "1")
        monkeypatch.setattr(spec_decode, "propose",
                            _oracle_drafter([base_a, base_b]))
    adapter = None
    if use_lora:
        # Zero-init adapter: serves exactly the base model, so the LoRA
        # row path (pack bytes, adapter attribution) runs under parity.
        cfg = lora.validate_config({"rank": 4})
        params = lora.init_params(gpt_model.arch, cfg, seed=7)
        lora.save_adapter("memled-a", "memgpt", cfg, params,
                          {"code": "Created"}, sync_flush=True)
        adapter = adapters.REGISTRY.acquire("memled-a", "memgpt")

    engine = make_engine("memgpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 6, adapter=adapter)
    cb = _submit(engine, pb, 8)
    # A mid-flight snapshot (any live row) seeds the high-water marks.
    deadline = time.monotonic() + 60
    while engine.active_rows == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    engine.memory_snapshot()
    assert ca.result() == base_a
    assert cb.result() == base_b
    # second wave: prefix-cache hits (when on) over cached pages
    assert _submit(engine, pa, 6, adapter=adapter).result() == base_a
    _settle(engine)

    snap = engine.memory_snapshot()
    states = snap["pool_pages"]
    assert sum(states.values()) == snap["pool_pages_total"]
    assert all(n >= 0 for n in states.values())
    assert engine._ledger.audit("test-final") == []
    assert snap["audit_failures"] == 0
    assert snap["kv_pool_capacity_drops"] == 0
    assert snap["unpin_underflows"] == 0
    if paged:
        assert snap["paged"] is True
        assert snap["page_size"] == PAGE
        assert snap["pool_pages_total"] > 0
        # engine idle: nothing owned by rows, nothing pinned or held
        assert states["row"] == 0
        assert states["prefix_pinned"] == 0
        assert states["preempted"] == 0
        # retirements inserted pages into the radix cache
        assert states["prefix_evictable"] > 0
        assert snap["tenant_pages"] == {}
        assert snap["high_water_pages"]["used"] >= 1
        assert snap["high_water_pages"]["row"] >= 1
    else:
        assert snap["paged"] is False
        assert snap["pool_pages_total"] == 0
    hbm = snap["hbm_bytes"]
    assert hbm["kv_values"] > 0
    assert hbm["params"] > 0
    assert (hbm["kv_scales"] > 0) == bool(int8)
    assert (hbm["lora_pack"] > 0) == bool(use_lora)


# ---------------------------------------------------------------------------
# GET /memory/ attribution vs. the independent block-table walk
# ---------------------------------------------------------------------------

def test_mixed_tenant_attribution_matches_block_table_walk(
        gpt_model, client, paged_env):
    """Three live rows (tenants a, a, b) slowed mid-decode by a sleep
    fault: GET /memory/ per-tenant page counts equal the independent
    device block-table walk, and the /metrics tenant/pool gauges agree.

    The three views cannot be read under one lock (the HTTP handlers run
    the snapshot on an executor thread, which would deadlock on the
    engine lock this thread held), so consistency comes from a
    read-walk-read sandwich instead: live rows only GROW their page
    counts, so when the two HTTP reads on either side of the lock-held
    walk agree, the walk's value is squeezed between them and all three
    describe the same state."""
    from penroz_tpu.serve import decode_scheduler
    paged_env.setenv(faults.ENV, "decode.step:sleep@400")
    engine = decode_scheduler.get_engine("memgpt", BLOCK, 0.0, None)
    cols = [_submit(engine, [1, 2, 3, 4, 5], 8, tenant="tenant-a"),
            _submit(engine, [7, 8, 9], 8, tenant="tenant-a"),
            _submit(engine, [11, 12, 13, 14, 15], 8, tenant="tenant-b")]
    def rows_prefilled():
        """All three rows live with KV written (zero-length rows own no
        pages yet — the interesting attribution starts after prefill)."""
        with engine._cond:
            live = [i for i, r in enumerate(engine._rows) if r is not None]
            return (len(live) == 3
                    and all(int(engine._lengths[i]) > 0 for i in live))

    deadline = time.monotonic() + 120
    while not rows_prefilled():
        assert time.monotonic() < deadline, "rows never all prefilled"
        time.sleep(0.02)

    def mem_entry():
        status, body = _json(client, "GET", "/memory/")
        assert status == 200 and body["memledger_enabled"] is True
        return body, next(e for e in body["engines"]
                          if e["model_id"] == "memgpt")

    matched = False
    while not matched:
        assert time.monotonic() < deadline, \
            "no stable read-walk-read window before the rows retired"
        body1, e1 = mem_entry()
        mstatus, mbody = _request(client, "GET", "/metrics")
        with engine._cond:
            live = sum(r is not None for r in engine._rows)
            truth_rows, truth_tenants = _block_table_walk(engine)
        body2, e2 = mem_entry()
        if live < 3 or (e1["tenant_pages"], e1["pool_pages"]["row"]) != \
                (e2["tenant_pages"], e2["pool_pages"]["row"]):
            continue  # a tick advanced mid-sandwich; try again
        matched = True

    assert e1["tenant_pages"] == truth_tenants
    assert set(truth_tenants) == {"tenant-a", "tenant-b"}
    assert e1["pool_pages"]["row"] == truth_rows
    assert truth_rows >= 3  # every live row owns at least one page
    assert sum(e1["pool_pages"].values()) == e1["pool_pages_total"]
    # the aggregate view is the same single engine
    assert body1["pool_pages"] == e1["pool_pages"]
    assert body1["tenant_pages"] == truth_tenants

    assert mstatus.status == 200
    text = mbody.decode()

    def gauge(name, **labels):
        lab = ("{" + ",".join(f'{k}="{v}"' for k, v in labels.items()) + "}"
               if labels else "")
        m = re.search(rf"^{re.escape(name + lab)} (\S+)$", text, re.M)
        assert m, f"no sample for {name}{lab}"
        return float(m.group(1))

    for tenant, pages in truth_tenants.items():
        assert gauge("penroz_tenant_kv_pages", tenant=tenant) == pages
    assert gauge("penroz_pool_pages", state="row") == truth_rows
    assert gauge("penroz_pool_pages", state="free") == \
        e1["pool_pages"]["free"]

    for c in cols:
        c.result()
    _settle(engine)
    final = engine.memory_snapshot()
    assert final["pool_pages"]["row"] == 0
    assert final["tenant_pages"] == {}


# ---------------------------------------------------------------------------
# Chaos sites: every injected crash leaves a provably clean pool
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec_site", [
    ("decode.step:raise@2", False),
    ("decode.prefill_chunk:raise@1", False),
    ("decode.verify:raise@1", True)],
    ids=["step", "prefill_chunk", "verify"])
def test_chaos_fault_sites_leave_clean_ledger(gpt_model, make_engine,
                                              paged_env, spec_site):
    """Each registered decode fault site crashes the engine mid-flight;
    strict mode audited crash recovery INSIDE the worker (a leaked page
    there would open the breaker), the resubmitted request is
    greedy-identical, and the final explicit audit is clean."""
    site, need_spec = spec_site
    from penroz_tpu.serve import spec_decode
    base = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 6,
                                     temperature=0.0)
    if need_spec:
        paged_env.setenv("PENROZ_SPEC_DECODE", "1")
        paged_env.setattr(spec_decode, "propose", _oracle_drafter([base]))
    paged_env.setenv(faults.ENV, site)
    engine = make_engine("memgpt", BLOCK, 0.0, None, capacity=2)
    with pytest.raises(faults.InjectedFault):
        _submit(engine, REP_PROMPT, 6).result()
    paged_env.delenv(faults.ENV)
    faults.reset()
    assert _submit(engine, REP_PROMPT, 6).result() == base
    _settle(engine)
    stats = engine.stats()
    assert stats["crashes_total"] == 1
    assert stats["breaker_open"] is False  # strict recovery audit passed
    snap = engine.memory_snapshot()
    assert sum(snap["pool_pages"].values()) == snap["pool_pages_total"]
    assert snap["pool_pages"]["row"] == 0
    assert snap["pool_pages"]["prefix_pinned"] == 0
    assert engine._ledger.audit("test-after-crash") == []
    assert snap["audit_failures"] == 0


# ---------------------------------------------------------------------------
# Flight recorder: GET /debug/dump serves the pre-crash evidence
# ---------------------------------------------------------------------------

def test_debug_dump_captures_pre_crash_ledger(gpt_model, client, paged_env):
    """decode.step:raise@3 kills the third decode tick; the recorder
    snapshots BEFORE _fail_all/_alloc_state destroy the state, so the
    dump's ledger still shows the crashed row's pages and the tick
    timeline that led up to it."""
    from penroz_tpu.serve import decode_scheduler
    paged_env.setenv(faults.ENV, "decode.step:raise@3")
    engine = decode_scheduler.get_engine("memgpt", BLOCK, 0.0, None)
    with pytest.raises(faults.InjectedFault):
        _submit(engine, [1, 2, 3, 4, 5], 10).result()

    status, dump = _json(client, "GET", "/debug/dump")
    assert status == 200
    assert dump["capacity"] == 8  # PENROZ_DEBUG_DUMP_RING default
    assert dump["recorded"] == 1 and len(dump["entries"]) == 1
    entry = dump["entries"][0]
    assert entry["reason"] == "engine_crash"
    assert "InjectedFault" in entry["error"]
    assert entry["model_id"] == "memgpt"
    assert entry["crashes_total"] == 1
    assert entry["active_rows"] == 1
    # the PRE-crash ledger: the dying row still owns its pages
    ledger = entry["ledger"]
    assert ledger["paged"] is True
    assert ledger["pool_pages"]["row"] >= 1
    assert sum(ledger["pool_pages"].values()) == ledger["pool_pages_total"]
    # tick timeline tail + queue state + trace correlation keys
    assert entry["tick_timeline"]
    assert all("age_s" in t for t in entry["tick_timeline"])
    assert isinstance(entry["queue_depth_by_class"], dict)
    assert isinstance(entry["queue_depth_by_tenant"], dict)
    assert set(entry["recent_traces"]) == {"completed", "live"}

    # the aggregate ledger carries the recorder count, and the engine
    # came back with a clean (reset) pool
    status, mem = _json(client, "GET", "/memory/")
    assert status == 200 and mem["flight_records"] == 1
    mentry = next(e for e in mem["engines"] if e["model_id"] == "memgpt")
    assert mentry["pool_pages"]["row"] == 0
    assert mentry["audit_failures"] == 0


# ---------------------------------------------------------------------------
# /metrics gauge exposure + engine-scoped counter attribution
# ---------------------------------------------------------------------------

def test_metrics_memory_gauge_families(gpt_model, client, paged_env):
    """After one completed request every capacity-ledger gauge family is
    declared and the labeled series match the engine snapshot (the
    partition sum shows up ON the scrape: states sum to capacity)."""
    from penroz_tpu.serve import decode_scheduler, memledger
    engine = decode_scheduler.get_engine("memgpt", BLOCK, 0.0, None)
    _submit(engine, [1, 2, 3, 4, 5], 6).result()
    _settle(engine)
    status, body = _request(client, "GET", "/metrics")
    assert status.status == 200
    text = body.decode()
    for fam in ("penroz_pool_pages", "penroz_pool_pages_hwm",
                "penroz_tenant_kv_pages", "penroz_hbm_bytes",
                "penroz_kv_time_to_exhaustion_s"):
        assert f"# TYPE {fam} gauge" in text

    def gauge(name, **labels):
        lab = "{" + ",".join(f'{k}="{v}"' for k, v in labels.items()) + "}"
        m = re.search(rf"^{re.escape(name + lab)} (\S+)$", text, re.M)
        assert m, f"no sample for {name}{lab}"
        return float(m.group(1))

    snap = engine.memory_snapshot()
    for state in memledger.PAGE_STATES:
        assert gauge("penroz_pool_pages", state=state) == \
            snap["pool_pages"][state]
    assert sum(gauge("penroz_pool_pages", state=s)
               for s in memledger.PAGE_STATES) == snap["pool_pages_total"]
    assert gauge("penroz_pool_pages_hwm", state="used") >= 1
    assert gauge("penroz_hbm_bytes", component="kv_values") > 0
    assert gauge("penroz_hbm_bytes", component="params") > 0
    assert gauge("penroz_hbm_bytes", component="adapter_host_cache") >= 0
    # TTE is absent-or-nonnegative, never a misleading rendered zero
    m = re.search(r"^penroz_kv_time_to_exhaustion_s (\S+)$", text, re.M)
    if m:
        assert float(m.group(1)) >= 0


def test_engine_scoped_drop_and_underflow_attribution(gpt_model,
                                                      make_engine):
    """Satellite 1: the ledger refines the process-wide KV globals into
    per-engine attribution — engine counters move without touching the
    byte-compatible /metrics totals, and the underflow carry survives
    crash-recovery cache replacement."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import memledger
    engine = make_engine("memgpt", BLOCK, 0.0, None, capacity=2)
    _submit(engine, [1, 2, 3], 4).result()
    _settle(engine)
    assert engine.stats()["kv_pool_capacity_drops"] == 0

    engine._ledger.note_pool_drop(5)
    stats = engine.stats()
    assert stats["kv_pool_capacity_drops"] == 1
    snap = engine.memory_snapshot()
    assert snap["kv_pool_capacity_drops"] == 1
    assert snap["pressure_events"] == 1
    assert engine._ledger.dropped_tokens == 5
    # the process-wide total (what /metrics exports) is untouched: the
    # engine-scoped ledger refines it, never double-counts into it
    assert KV.pool_drop_count() == 0
    assert memledger.memory_stats()["kv_pool_capacity_drops"] == 0

    # crash recovery replaces the prefix cache; the dying instance's
    # underflow count folds into the lifetime carry
    class _DyingCache:
        unpin_underflows = 3

    assert engine.stats()["unpin_underflows"] == 0
    engine._ledger.on_realloc(_DyingCache())
    assert engine._ledger.unpin_underflows == 3
    assert engine.stats()["unpin_underflows"] == 3
    assert KV.unpin_underflow_count() == 0


# ---------------------------------------------------------------------------
# Kill switch: PENROZ_MEMLEDGER=0 degrades to zeros, never to lies
# ---------------------------------------------------------------------------

def test_ledger_disabled_degrades_gracefully(gpt_model, make_engine,
                                             paged_env):
    """With the ledger off: serving is untouched (greedy parity), the
    snapshot reports zeros instead of guesses, audits are no-ops even in
    strict mode, and the flight recorder drops its captures."""
    from penroz_tpu.serve import memledger
    paged_env.setenv("PENROZ_MEMLEDGER", "0")
    base = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 6,
                                     temperature=0.0)
    engine = make_engine("memgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, REP_PROMPT, 6).result() == base
    _settle(engine)
    snap = engine.memory_snapshot()
    assert snap["pool_pages_total"] == 0
    assert all(n == 0 for n in snap["pool_pages"].values())
    assert all(n == 0 for n in snap["hbm_bytes"].values())
    assert engine._ledger.audit("disabled") == []
    memledger.FLIGHT_RECORDER.record(engine, "engine_crash")
    assert memledger.FLIGHT_RECORDER.recorded == 0
    stats = memledger.memory_stats()
    assert stats["memledger_enabled"] is False
