"""Deterministic fault-injection registry (utils/faults.py).

The registry is the substrate every PR-3 recovery test stands on, so its
own semantics are pinned first: exact-Nth and open-ended raise rules,
sleep rules, rule composition, the disabled fast path, and the ckpt.write
production hook (a failed checkpoint write must roll back cleanly, never
leave a torn file).
"""

import glob
import os
import time

import numpy as np
import pytest

from penroz_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_registry(monkeypatch):
    monkeypatch.delenv(faults.ENV, raising=False)
    faults.reset()
    yield
    faults.reset()


def test_disabled_is_noop_and_counts_nothing():
    for _ in range(3):
        faults.check("decode.step")
    assert faults.call_count("decode.step") == 0


def test_raise_on_exact_nth_call(monkeypatch):
    monkeypatch.setenv(faults.ENV, "decode.step:raise@2")
    faults.check("decode.step")                      # call 1: fine
    with pytest.raises(faults.InjectedFault, match="decode.step"):
        faults.check("decode.step")                  # call 2: armed
    faults.check("decode.step")                      # call 3: fine again
    assert faults.call_count("decode.step") == 3


def test_open_ended_raise_from_nth_call(monkeypatch):
    monkeypatch.setenv(faults.ENV, "s:raise@2+")
    faults.check("s")
    for _ in range(3):
        with pytest.raises(faults.InjectedFault):
            faults.check("s")


def test_rules_compose_and_sites_are_independent(monkeypatch):
    monkeypatch.setenv(faults.ENV, "a:raise@1,a:raise@2,b:raise@1")
    with pytest.raises(faults.InjectedFault):
        faults.check("a")
    with pytest.raises(faults.InjectedFault):
        faults.check("a")
    faults.check("a")                                # a survives call 3
    with pytest.raises(faults.InjectedFault):
        faults.check("b")                            # b has its own counter
    faults.check("unarmed.site")                     # never armed: no-op


def test_sleep_rule_sleeps_roughly_the_requested_ms(monkeypatch):
    monkeypatch.setenv(faults.ENV, "slow:sleep@50")
    t0 = time.monotonic()
    faults.check("slow")
    assert time.monotonic() - t0 >= 0.045


def test_unparseable_rules_are_ignored_not_fatal(monkeypatch):
    monkeypatch.setenv(faults.ENV, "garbage,a:explode@1,a:raise@nan,"
                                   "a:raise@1")
    with pytest.raises(faults.InjectedFault):
        faults.check("a")


def test_reset_clears_counters_and_respec(monkeypatch):
    monkeypatch.setenv(faults.ENV, "s:raise@1")
    with pytest.raises(faults.InjectedFault):
        faults.check("s")
    faults.reset()
    with pytest.raises(faults.InjectedFault):        # counter back to 0
        faults.check("s")


def test_ckpt_write_fault_rolls_back_cleanly(workdir, monkeypatch):
    """The ckpt.write production hook: an injected write failure surfaces
    to the caller and leaves NO file behind — neither the target nor a
    temp sibling (the atomic-write contract under failure)."""
    from penroz_tpu.utils import checkpoint
    monkeypatch.setenv(faults.ENV, "ckpt.write:raise@1")
    faults.reset()
    with pytest.raises(faults.InjectedFault):
        checkpoint.save("faulty", {"status": {"code": "Created"},
                                   "params": {"w": np.ones(4, np.float32)}},
                        sync_flush=True)
    leftovers = (glob.glob(os.path.join(checkpoint.SHM_PATH, "models", "*"))
                 + glob.glob("models/*"))
    assert leftovers == [], leftovers
    # the next write (call 2, unarmed) succeeds
    checkpoint.save("faulty", {"status": {"code": "Created"},
                               "params": {"w": np.ones(4, np.float32)}},
                    sync_flush=True)
    assert checkpoint.load("faulty")["status"]["code"] == "Created"
