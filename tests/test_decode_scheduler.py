"""Continuous-batching decode scheduler tests (serve/decode_scheduler.py).

Tier-1-safe: CPU, small shapes, no `slow` marker.  The parity contract is
the load-bearing one — every greedy sequence the scheduler returns must be
token-identical to the same request run alone through the legacy
single-sequence path, under concurrency, mid-flight admission, and slot
recycling.
"""

import asyncio
import queue
import time

import numpy as np
import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

# CI tier: heavier compiles (serving stack), same tier as test_app.
pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}


@pytest.fixture(autouse=True)
def _scheduler_registry(workdir):
    """Fresh engine registry per test: engines cache model snapshots by id,
    and every test gets its own checkpoint dir (workdir)."""
    from penroz_tpu.serve import decode_scheduler
    yield
    decode_scheduler.reset()


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    """A serialized toy GPT (attention + KV cache on the decode path)."""
    model = NeuralNetworkModel("schedgpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def make_engine():
    """Directly constructed engines (registry-bypassing tests) must not leak
    worker threads into later tests."""
    from penroz_tpu.serve import decode_scheduler
    engines = []

    def build(*args, **kwargs):
        engine = decode_scheduler.DecodeEngine(*args, **kwargs)
        engines.append(engine)
        return engine

    yield build
    for engine in engines:
        engine.shutdown()


class _Collector:
    """Thread-queue consumer for engine-level tests (the async layer is
    exercised separately through the HTTP routes)."""

    def __init__(self, prompt):
        self.q = queue.Queue()
        self.tokens = list(prompt)
        self.received = 0

    def on_event(self, kind, value):
        self.q.put((kind, value))

    def result(self, timeout=180):
        deadline = time.monotonic() + timeout
        while True:
            kind, value = self.q.get(
                timeout=max(deadline - time.monotonic(), 0.1))
            if kind == "token":
                self.tokens.append(value)
                self.received += 1
            elif kind == "done":
                return self.tokens
            else:
                raise value


def _submit(engine, prompt, max_new, stop_token=None):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt)
    engine.submit(decode_scheduler.Request(prompt, max_new, stop_token,
                                           collector.on_event))
    return collector


def test_concurrent_parity_two_overlapping_requests(gpt_model, make_engine):
    """Two overlapping greedy requests through one shared batch return
    exactly the tokens each returns when run alone."""
    from penroz_tpu.serve import decode_scheduler
    p1, p2 = [1, 2, 3], [5]
    max_new = 6
    base1 = gpt_model.generate_tokens([p1], BLOCK, max_new, temperature=0.0)
    base2 = gpt_model.generate_tokens([p2], BLOCK, max_new, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    c1 = _submit(engine, p1, max_new)
    c2 = _submit(engine, p2, max_new)
    assert c1.result() == base1
    assert c2.result() == base2


def test_mid_flight_admission(gpt_model, make_engine):
    """Request B admitted while A is mid-decode; both finish with their
    standalone token sequences (admission happens at a step boundary and
    prefills into a free row of the live batch)."""
    from penroz_tpu.serve import decode_scheduler
    pa, pb = [9, 10, 11], [4, 5]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 10, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 10)
    deadline = time.monotonic() + 120
    while ca.received < 2:  # A provably mid-decode before B arrives
        assert time.monotonic() < deadline, "A never started decoding"
        try:
            kind, value = ca.q.get(timeout=1.0)
        except queue.Empty:
            continue
        assert kind == "token", kind
        ca.tokens.append(value)
        ca.received += 1
    cb = _submit(engine, pb, 4)
    assert cb.result() == base_b
    assert ca.result() == base_a
    assert engine.stats()["completed"] == 2


def test_slot_recycling_capacity_2_serves_4(gpt_model, make_engine):
    """A capacity-2 engine serves 4 requests: retired rows recycle their KV
    slot for the queued requests, all outputs match the standalone path."""
    from penroz_tpu.serve import decode_scheduler
    prompts = [[1, 2, 3], [5], [7, 8], [9, 10, 11, 12]]
    max_new = 5
    bases = [gpt_model.generate_tokens([p], BLOCK, max_new, temperature=0.0)
             for p in prompts]
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    collectors = [_submit(engine, p, max_new) for p in prompts]
    for base, collector in zip(bases, collectors):
        assert collector.result() == base
    stats = engine.stats()
    assert stats["capacity"] == 2
    assert stats["admissions"] == 4
    assert stats["completed"] == 4
    assert stats["decode_tokens"] > 0
    assert 0.0 < stats["occupancy_avg"] <= 1.0


def test_stop_token_retires_row_early(gpt_model, make_engine):
    from penroz_tpu.serve import decode_scheduler
    prompt, max_new = [1, 2, 3], 6
    base = gpt_model.generate_tokens([prompt], BLOCK, max_new,
                                     temperature=0.0)
    stop = base[len(prompt)]  # first generated token
    base_stop = gpt_model.generate_tokens([prompt], BLOCK, max_new,
                                          temperature=0.0, stop_token=stop)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, prompt, max_new, stop_token=stop).result() \
        == base_stop
    assert engine.stats()["completed"] == 1


def test_batch_overflow_rows_rejected_with_row_index(gpt_model):
    """Satellite: the batched path names the overflowing rows in its 400
    instead of silently truncating (no crop/re-prefill on that path)."""
    with pytest.raises(ValueError, match="row 1"):
        gpt_model.generate_tokens_batched([[1, 2], [1] * 14], BLOCK, 6,
                                          temperature=0.0)
    from penroz_tpu.models.model import validate_batch_generation
    with pytest.raises(ValueError, match="row 0"):
        validate_batch_generation([[1] * 15], BLOCK, 6)
    validate_batch_generation([[1] * 10], BLOCK, 6)  # exactly fits: ok


# -- HTTP surface ------------------------------------------------------------

@pytest.fixture
def client(workdir):
    from penroz_tpu.serve import app as app_mod
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    from aiohttp.test_utils import TestClient, TestServer
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield client, loop
    loop.run_until_complete(client.close())
    loop.close()


def _json(client_loop, method, path, **kw):
    client, loop = client_loop

    async def go():
        resp = await client.request(method, path, **kw)
        import json as _json_mod
        body = await resp.read()
        return resp.status, (_json_mod.loads(body) if body else None)

    return loop.run_until_complete(go())


def _gen_payload(**overrides):
    payload = {"model_id": "schedgpt", "input": [[1, 2, 3]],
               "block_size": BLOCK, "max_new_tokens": 4, "temperature": 0.0}
    payload.update(overrides)
    return payload


def test_generate_routes_through_scheduler(client, gpt_model, monkeypatch):
    """With PENROZ_CONTINUOUS_BATCHING=1 the /generate/ response is
    token-identical to the legacy path, /serving_stats/ reports the engine,
    and concurrent requests coalesce into the shared batch."""
    status, legacy = _json(client, "POST", "/generate/",
                           json=_gen_payload())
    assert status == 200
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    status, routed = _json(client, "POST", "/generate/",
                           json=_gen_payload())
    assert status == 200
    assert routed["tokens"] == legacy["tokens"]

    # concurrent requests, each equal to its solo baseline
    test_client, loop = client

    async def one(i):
        resp = await test_client.post(
            "/generate/", json=_gen_payload(input=[[1 + i, 2]]))
        body = await resp.json()
        assert resp.status == 200, body
        return body["tokens"]

    async def run_all():
        return await asyncio.gather(*[one(i) for i in range(3)])

    concurrent = loop.run_until_complete(run_all())
    monkeypatch.delenv("PENROZ_CONTINUOUS_BATCHING")
    for i, row in enumerate(concurrent):
        status, solo = _json(client, "POST", "/generate/",
                             json=_gen_payload(input=[[1 + i, 2]]))
        assert solo["tokens"] == row

    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200
    assert stats["engines"], stats
    engine = stats["engines"][0]
    assert engine["model_id"] == "schedgpt"
    assert engine["completed"] >= 4
    assert stats["decode_tokens_per_sec"] >= 0
    assert "kv_pool_capacity_drops" in stats
    assert stats["admission_latency_ms_p50"] is not None


def test_generate_streaming_through_scheduler(client, gpt_model,
                                              monkeypatch):
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    test_client, loop = client

    async def go():
        resp = await test_client.post("/generate/",
                                      json=_gen_payload(stream=True))
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        return (await resp.read()).decode()

    body = loop.run_until_complete(go())
    streamed = [int(line) for line in body.strip().split("\n")]
    monkeypatch.delenv("PENROZ_CONTINUOUS_BATCHING")
    status, legacy = _json(client, "POST", "/generate/",
                           json=_gen_payload())
    assert streamed == legacy["tokens"][3:]  # generated tail only


def test_generate_batch_through_scheduler(client, gpt_model, monkeypatch):
    payload = {"model_id": "schedgpt", "inputs": [[1, 2, 3], [5]],
               "block_size": BLOCK, "max_new_tokens": 4, "temperature": 0.0}
    status, legacy = _json(client, "POST", "/generate_batch/", json=payload)
    assert status == 200
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    status, routed = _json(client, "POST", "/generate_batch/", json=payload)
    assert status == 200
    assert routed["sequences"] == legacy["sequences"]
    # per-row overflow → 400 naming the row, scheduler path included
    status, body = _json(client, "POST", "/generate_batch/", json=dict(
        payload, inputs=[[1, 2], [1] * 14]))
    assert status == 400
    assert "row 1" in body["detail"]


def test_serving_stats_disabled_and_openapi(client, workdir):
    """/serving_stats/ answers even with the scheduler off, and the OpenAPI
    spec documents the endpoint + response schema."""
    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200
    assert stats["continuous_batching_enabled"] is False
    assert stats["engines"] == []
    assert stats["kv_pool_capacity_drops"] >= 0
    status, spec = _json(client, "GET", "/openapi.json")
    assert "/serving_stats/" in spec["paths"]
    assert "ServingStatsResponse" in spec["components"]["schemas"]


def test_oversized_request_falls_back_to_legacy_path(client, gpt_model,
                                                     monkeypatch):
    """A prompt+max_new that exceeds block_size is NOT scheduler-eligible
    (no crop/re-prefill in the shared batch) — it must still succeed via
    the legacy path's crop/re-prefill loop."""
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    status, body = _json(client, "POST", "/generate/", json=_gen_payload(
        input=[[1, 2, 3, 4, 5]], max_new_tokens=14))
    assert status == 200
    assert len(body["tokens"]) == 19
    status, stats = _json(client, "GET", "/serving_stats/")
    assert stats["engines"] == []  # never touched the scheduler


# -- chunked prefill + radix prefix-KV cache (PR 2) --------------------------

@pytest.fixture
def prefix_env(monkeypatch):
    """Paged pool + radix prefix cache + small chunks, sized to BLOCK=16
    toy prompts (page = 4 tokens, cache region = 8 pages)."""
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "4")
    return monkeypatch


def test_chunked_prefill_parity_and_stall_bound(gpt_model, make_engine,
                                                monkeypatch):
    """A long prompt admitted mid-flight is prefilled in chunks interleaved
    with the shared decode steps: both requests keep their standalone
    greedy streams, and the decode batch is never stalled by more than ONE
    chunk between consecutive steps (the acceptance bound; the admission
    latency p50 reflects that interleaving instead of a full-prompt
    stall)."""
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "2")
    pa, pb = [5], [9, 10, 11, 12, 13, 14, 15]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 6, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 8)
    deadline = time.monotonic() + 120
    while ca.received < 2:  # A provably mid-decode before B arrives
        assert time.monotonic() < deadline, "A never started decoding"
        try:
            kind, value = ca.q.get(timeout=1.0)
        except queue.Empty:
            continue
        assert kind == "token", kind
        ca.tokens.append(value)
        ca.received += 1
    cb = _submit(engine, pb, 6)
    assert cb.result() == base_b
    assert ca.result() == base_a
    stats = engine.stats()
    # chunk plans: A = [1], B = [2, 2, 2, 1] (pow-2-bucketed tail)
    assert stats["prefill_chunks"] == 5
    # the acceptance bound: at most one chunk ever ran between two decode
    # steps (PENROZ_SCHED_MAX_STALL_MS defaults to 0)
    assert stats["prefill_max_chunks_between_steps"] == 1
    assert stats["prefill_chunk_stall_ms_p99"] is not None
    assert stats["admission_latency_ms_p50"] is not None
    assert stats["admission_latency_ms_p50"] > 0


def test_chunked_vs_oneshot_prefill_identical(gpt_model, make_engine,
                                              monkeypatch):
    """Greedy parity between one-dispatch prefill (chunk >= prompt, pow-2
    prompt length) and many-chunk prefill of the same prompt."""
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]  # 8 = one chunk at PENROZ_PREFILL_CHUNK=8
    base = gpt_model.generate_tokens([prompt], BLOCK, 6, temperature=0.0)
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "8")
    one_shot = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(one_shot, prompt, 6).result() == base
    assert one_shot.stats()["prefill_chunks"] == 1
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "2")
    chunked = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(chunked, prompt, 6).result() == base
    assert chunked.stats()["prefill_chunks"] == 4


def test_prefix_cache_hit_miss_parity(gpt_model, make_engine, prefix_env):
    """The greedy parity matrix over the radix cache: (miss), (hit on a
    different suffix), (repeat hit) — every stream token-identical to the
    standalone path, with the hits aliasing the shared prefix's pages
    (hit_tokens counts the skipped prefill)."""
    prefix = [1, 2, 3, 4, 5, 6, 7, 8]          # 2 full pages
    px, py = prefix + [9, 10], prefix + [11]
    base_x = gpt_model.generate_tokens([px], BLOCK, 4, temperature=0.0)
    base_y = gpt_model.generate_tokens([py], BLOCK, 4, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, px, 4).result() == base_x   # miss
    assert _submit(engine, py, 4).result() == base_y   # hit (shared prefix)
    assert _submit(engine, px, 4).result() == base_x   # repeat hit
    pc = engine.stats()["prefix_cache"]
    assert pc["misses"] == 1 and pc["hits"] == 2, pc
    assert pc["hit_tokens"] == 16  # 2 pages x 4 tokens x 2 hits
    assert pc["hit_rate"] == pytest.approx(2 / 3)


def test_prefix_cache_eviction_then_rematch_parity(gpt_model, make_engine,
                                                   prefix_env):
    """Eviction correctness: churn distinct prefixes through a 4-page cache
    region until the first prefix is LRU-evicted, then resubmit it — the
    re-prefilled (and re-registered) stream is token-identical."""
    prefix_env.setenv("PENROZ_PREFIX_CACHE_PAGES", "4")
    pa = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 4, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, pa, 4).result() == base_a
    for j in range(3):  # 3 distinct 2-page prefixes overflow 4 pages
        p = [20 + j] * 8 + [j]
        base = gpt_model.generate_tokens([p], BLOCK, 3, temperature=0.0)
        assert _submit(engine, p, 3).result() == base
    pc = engine.stats()["prefix_cache"]
    assert pc["evicted_pages"] > 0, pc
    assert _submit(engine, pa, 4).result() == base_a  # evicted → recompute
    pc = engine.stats()["prefix_cache"]
    assert pc["capacity_pages"] == 4


def test_serving_stats_reports_prefix_and_chunk_fields(client, gpt_model,
                                                       prefix_env):
    """/serving_stats/ carries the new observability: prefix-cache hit
    rate + evictions and the prefill chunk-stall p99, per engine and
    aggregated (dashboard tile inputs), validated against the schema."""
    prefix_env.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    payload = _gen_payload(input=[[1, 2, 3, 4, 5, 6, 7, 8, 9]])
    status, first = _json(client, "POST", "/generate/", json=payload)
    assert status == 200
    status, second = _json(client, "POST", "/generate/", json=payload)
    assert status == 200
    assert second["tokens"] == first["tokens"]
    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200
    assert stats["prefix_cache_hit_rate"] == pytest.approx(0.5)
    assert stats["prefix_cache_evicted_pages"] == 0
    assert "prefill_chunk_stall_ms_p99" in stats
    engine = stats["engines"][0]
    assert engine["prefill_chunks"] >= 2
    assert engine["prefix_cache"]["hits"] == 1
    assert engine["prefix_cache"]["misses"] == 1
    assert engine["prefix_cache"]["hit_tokens"] == 8
    assert engine["prefill_max_chunks_between_steps"] <= 1


def test_max_stall_budget_runs_multiple_chunks(gpt_model, make_engine,
                                               monkeypatch):
    """PENROZ_SCHED_MAX_STALL_MS > 0 trades inter-token latency for
    admission speed: with a generous budget, several chunks run between
    decode steps (the default budget of 0 pins that at one)."""
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "1")
    monkeypatch.setenv("PENROZ_SCHED_MAX_STALL_MS", "60000")
    pa, pb = [5], [9, 10, 11, 12, 13, 14]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 8)
    deadline = time.monotonic() + 120
    while ca.received < 2:
        assert time.monotonic() < deadline, "A never started decoding"
        try:
            kind, value = ca.q.get(timeout=1.0)
        except queue.Empty:
            continue
        ca.tokens.append(value)
        ca.received += 1
    cb = _submit(engine, pb, 4)
    assert cb.result() == base_b
    assert ca.result() == base_a
    # all 6 of B's 1-token chunks fit one boundary under the huge budget
    assert engine.stats()["prefill_max_chunks_between_steps"] == 6
