"""Continuous-batching decode scheduler tests (serve/decode_scheduler.py).

Tier-1-safe: CPU, small shapes, no `slow` marker.  The parity contract is
the load-bearing one — every greedy sequence the scheduler returns must be
token-identical to the same request run alone through the legacy
single-sequence path, under concurrency, mid-flight admission, and slot
recycling.
"""

import asyncio
import queue
import time

import numpy as np
import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

# CI tier: heavier compiles (serving stack), same tier as test_app.
pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}


@pytest.fixture(autouse=True)
def _scheduler_registry(workdir):
    """Fresh engine registry + fault-injection counters + QoS quota state
    per test: engines cache model snapshots by id, and every test gets its
    own checkpoint dir (workdir)."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import decode_scheduler, qos
    from penroz_tpu.utils import faults
    faults.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()
    yield
    decode_scheduler.reset()
    faults.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    """A serialized toy GPT (attention + KV cache on the decode path)."""
    model = NeuralNetworkModel("schedgpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def make_engine():
    """Directly constructed engines (registry-bypassing tests) must not leak
    worker threads into later tests."""
    from penroz_tpu.serve import decode_scheduler
    engines = []

    def build(*args, **kwargs):
        engine = decode_scheduler.DecodeEngine(*args, **kwargs)
        engines.append(engine)
        return engine

    yield build
    for engine in engines:
        engine.shutdown()


class _Collector:
    """Thread-queue consumer for engine-level tests (the async layer is
    exercised separately through the HTTP routes)."""

    def __init__(self, prompt):
        self.q = queue.Queue()
        self.tokens = list(prompt)
        self.received = 0

    def on_event(self, kind, value):
        self.q.put((kind, value))

    def result(self, timeout=180):
        deadline = time.monotonic() + timeout
        while True:
            kind, value = self.q.get(
                timeout=max(deadline - time.monotonic(), 0.1))
            if kind == "token":
                self.tokens.append(value)
                self.received += 1
            elif kind == "done":
                return self.tokens
            else:
                raise value


def _submit(engine, prompt, max_new, stop_token=None, timeout_ms=None):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt)
    engine.submit(decode_scheduler.Request(prompt, max_new, stop_token,
                                           collector.on_event,
                                           timeout_ms=timeout_ms))
    return collector


def test_concurrent_parity_two_overlapping_requests(gpt_model, make_engine):
    """Two overlapping greedy requests through one shared batch return
    exactly the tokens each returns when run alone."""
    from penroz_tpu.serve import decode_scheduler
    p1, p2 = [1, 2, 3], [5]
    max_new = 6
    base1 = gpt_model.generate_tokens([p1], BLOCK, max_new, temperature=0.0)
    base2 = gpt_model.generate_tokens([p2], BLOCK, max_new, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    c1 = _submit(engine, p1, max_new)
    c2 = _submit(engine, p2, max_new)
    assert c1.result() == base1
    assert c2.result() == base2


def test_mid_flight_admission(gpt_model, make_engine):
    """Request B admitted while A is mid-decode; both finish with their
    standalone token sequences (admission happens at a step boundary and
    prefills into a free row of the live batch)."""
    from penroz_tpu.serve import decode_scheduler
    pa, pb = [9, 10, 11], [4, 5]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 10, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 10)
    deadline = time.monotonic() + 120
    while ca.received < 2:  # A provably mid-decode before B arrives
        assert time.monotonic() < deadline, "A never started decoding"
        try:
            kind, value = ca.q.get(timeout=1.0)
        except queue.Empty:
            continue
        assert kind == "token", kind
        ca.tokens.append(value)
        ca.received += 1
    cb = _submit(engine, pb, 4)
    assert cb.result() == base_b
    assert ca.result() == base_a
    assert engine.stats()["completed"] == 2


def test_slot_recycling_capacity_2_serves_4(gpt_model, make_engine):
    """A capacity-2 engine serves 4 requests: retired rows recycle their KV
    slot for the queued requests, all outputs match the standalone path."""
    from penroz_tpu.serve import decode_scheduler
    prompts = [[1, 2, 3], [5], [7, 8], [9, 10, 11, 12]]
    max_new = 5
    bases = [gpt_model.generate_tokens([p], BLOCK, max_new, temperature=0.0)
             for p in prompts]
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    collectors = [_submit(engine, p, max_new) for p in prompts]
    for base, collector in zip(bases, collectors):
        assert collector.result() == base
    stats = engine.stats()
    assert stats["capacity"] == 2
    assert stats["admissions"] == 4
    assert stats["completed"] == 4
    assert stats["decode_tokens"] > 0
    assert 0.0 < stats["occupancy_avg"] <= 1.0


def test_stop_token_retires_row_early(gpt_model, make_engine):
    from penroz_tpu.serve import decode_scheduler
    prompt, max_new = [1, 2, 3], 6
    base = gpt_model.generate_tokens([prompt], BLOCK, max_new,
                                     temperature=0.0)
    stop = base[len(prompt)]  # first generated token
    base_stop = gpt_model.generate_tokens([prompt], BLOCK, max_new,
                                          temperature=0.0, stop_token=stop)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, prompt, max_new, stop_token=stop).result() \
        == base_stop
    assert engine.stats()["completed"] == 1


def test_batch_overflow_rows_rejected_with_row_index(gpt_model):
    """Satellite: the batched path names the overflowing rows in its 400
    instead of silently truncating (no crop/re-prefill on that path)."""
    with pytest.raises(ValueError, match="row 1"):
        gpt_model.generate_tokens_batched([[1, 2], [1] * 14], BLOCK, 6,
                                          temperature=0.0)
    from penroz_tpu.models.model import validate_batch_generation
    with pytest.raises(ValueError, match="row 0"):
        validate_batch_generation([[1] * 15], BLOCK, 6)
    validate_batch_generation([[1] * 10], BLOCK, 6)  # exactly fits: ok


# -- HTTP surface ------------------------------------------------------------

@pytest.fixture
def client(workdir):
    from penroz_tpu.serve import app as app_mod
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    from aiohttp.test_utils import TestClient, TestServer
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield client, loop
    loop.run_until_complete(client.close())
    loop.close()


def _json(client_loop, method, path, **kw):
    client, loop = client_loop

    async def go():
        resp = await client.request(method, path, **kw)
        import json as _json_mod
        body = await resp.read()
        return resp.status, (_json_mod.loads(body) if body else None)

    return loop.run_until_complete(go())


def _gen_payload(**overrides):
    payload = {"model_id": "schedgpt", "input": [[1, 2, 3]],
               "block_size": BLOCK, "max_new_tokens": 4, "temperature": 0.0}
    payload.update(overrides)
    return payload


def test_generate_routes_through_scheduler(client, gpt_model, monkeypatch):
    """With PENROZ_CONTINUOUS_BATCHING=1 the /generate/ response is
    token-identical to the legacy path, /serving_stats/ reports the engine,
    and concurrent requests coalesce into the shared batch."""
    status, legacy = _json(client, "POST", "/generate/",
                           json=_gen_payload())
    assert status == 200
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    status, routed = _json(client, "POST", "/generate/",
                           json=_gen_payload())
    assert status == 200
    assert routed["tokens"] == legacy["tokens"]

    # concurrent requests, each equal to its solo baseline
    test_client, loop = client

    async def one(i):
        resp = await test_client.post(
            "/generate/", json=_gen_payload(input=[[1 + i, 2]]))
        body = await resp.json()
        assert resp.status == 200, body
        return body["tokens"]

    async def run_all():
        return await asyncio.gather(*[one(i) for i in range(3)])

    concurrent = loop.run_until_complete(run_all())
    monkeypatch.delenv("PENROZ_CONTINUOUS_BATCHING")
    for i, row in enumerate(concurrent):
        status, solo = _json(client, "POST", "/generate/",
                             json=_gen_payload(input=[[1 + i, 2]]))
        assert solo["tokens"] == row

    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200
    assert stats["engines"], stats
    engine = stats["engines"][0]
    assert engine["model_id"] == "schedgpt"
    assert engine["completed"] >= 4
    assert stats["decode_tokens_per_sec"] >= 0
    assert "kv_pool_capacity_drops" in stats
    assert stats["admission_latency_ms_p50"] is not None


def test_generate_streaming_through_scheduler(client, gpt_model,
                                              monkeypatch):
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    test_client, loop = client

    async def go():
        resp = await test_client.post("/generate/",
                                      json=_gen_payload(stream=True))
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        return (await resp.read()).decode()

    body = loop.run_until_complete(go())
    streamed = [int(line) for line in body.strip().split("\n")]
    monkeypatch.delenv("PENROZ_CONTINUOUS_BATCHING")
    status, legacy = _json(client, "POST", "/generate/",
                           json=_gen_payload())
    assert streamed == legacy["tokens"][3:]  # generated tail only


def test_generate_batch_through_scheduler(client, gpt_model, monkeypatch):
    payload = {"model_id": "schedgpt", "inputs": [[1, 2, 3], [5]],
               "block_size": BLOCK, "max_new_tokens": 4, "temperature": 0.0}
    status, legacy = _json(client, "POST", "/generate_batch/", json=payload)
    assert status == 200
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    status, routed = _json(client, "POST", "/generate_batch/", json=payload)
    assert status == 200
    assert routed["sequences"] == legacy["sequences"]
    # per-row overflow → 400 naming the row, scheduler path included
    status, body = _json(client, "POST", "/generate_batch/", json=dict(
        payload, inputs=[[1, 2], [1] * 14]))
    assert status == 400
    assert "row 1" in body["detail"]


def test_serving_stats_disabled_and_openapi(client, workdir):
    """/serving_stats/ answers even with the scheduler off, and the OpenAPI
    spec documents the endpoint + response schema."""
    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200
    assert stats["continuous_batching_enabled"] is False
    assert stats["engines"] == []
    assert stats["kv_pool_capacity_drops"] >= 0
    # fault-tolerance aggregates are present from day zero
    assert stats["queue_rejections"] == 0
    assert stats["deadline_timeouts"] == 0
    assert stats["breaker_open"] is False
    assert stats["crashes_total"] == 0
    assert stats["draining"] is False
    # speculative-decoding aggregates present from day zero
    assert stats["spec_decode_enabled"] is False
    assert stats["spec_accept_rate"] is None
    assert stats["tokens_per_decode_step"] == 0.0
    status, spec = _json(client, "GET", "/openapi.json")
    assert "/serving_stats/" in spec["paths"]
    assert "/healthz" in spec["paths"]
    assert "/readyz" in spec["paths"]
    assert "ServingStatsResponse" in spec["components"]["schemas"]
    gen = spec["paths"]["/generate/"]["post"]["responses"]
    assert {"429", "503", "504"} <= set(gen)


def test_oversized_request_falls_back_to_legacy_path(client, gpt_model,
                                                     monkeypatch):
    """A prompt+max_new that exceeds block_size is NOT scheduler-eligible
    (no crop/re-prefill in the shared batch) — it must still succeed via
    the legacy path's crop/re-prefill loop."""
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    status, body = _json(client, "POST", "/generate/", json=_gen_payload(
        input=[[1, 2, 3, 4, 5]], max_new_tokens=14))
    assert status == 200
    assert len(body["tokens"]) == 19
    status, stats = _json(client, "GET", "/serving_stats/")
    assert stats["engines"] == []  # never touched the scheduler


# -- chunked prefill + radix prefix-KV cache (PR 2) --------------------------

@pytest.fixture
def prefix_env(monkeypatch):
    """Paged pool + radix prefix cache + small chunks, sized to BLOCK=16
    toy prompts (page = 4 tokens, cache region = 8 pages)."""
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "4")
    return monkeypatch


def test_chunked_prefill_parity_and_stall_bound(gpt_model, make_engine,
                                                monkeypatch):
    """A long prompt admitted mid-flight is prefilled in chunks interleaved
    with the shared decode steps: both requests keep their standalone
    greedy streams, and the decode batch is never stalled by more than ONE
    chunk between consecutive steps (the acceptance bound; the admission
    latency p50 reflects that interleaving instead of a full-prompt
    stall)."""
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "2")
    pa, pb = [5], [9, 10, 11, 12, 13, 14, 15]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 6, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 8)
    deadline = time.monotonic() + 120
    while ca.received < 2:  # A provably mid-decode before B arrives
        assert time.monotonic() < deadline, "A never started decoding"
        try:
            kind, value = ca.q.get(timeout=1.0)
        except queue.Empty:
            continue
        assert kind == "token", kind
        ca.tokens.append(value)
        ca.received += 1
    cb = _submit(engine, pb, 6)
    assert cb.result() == base_b
    assert ca.result() == base_a
    stats = engine.stats()
    # chunk plans: A = [1], B = [2, 2, 2, 1] (pow-2-bucketed tail)
    assert stats["prefill_chunks"] == 5
    # the acceptance bound: at most one chunk ever ran between two decode
    # steps (PENROZ_SCHED_MAX_STALL_MS defaults to 0)
    assert stats["prefill_max_chunks_between_steps"] == 1
    assert stats["prefill_chunk_stall_ms_p99"] is not None
    assert stats["admission_latency_ms_p50"] is not None
    assert stats["admission_latency_ms_p50"] > 0


def test_chunked_vs_oneshot_prefill_identical(gpt_model, make_engine,
                                              monkeypatch):
    """Greedy parity between one-dispatch prefill (chunk >= prompt, pow-2
    prompt length) and many-chunk prefill of the same prompt."""
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]  # 8 = one chunk at PENROZ_PREFILL_CHUNK=8
    base = gpt_model.generate_tokens([prompt], BLOCK, 6, temperature=0.0)
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "8")
    one_shot = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(one_shot, prompt, 6).result() == base
    assert one_shot.stats()["prefill_chunks"] == 1
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "2")
    chunked = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(chunked, prompt, 6).result() == base
    assert chunked.stats()["prefill_chunks"] == 4


def test_prefix_cache_hit_miss_parity(gpt_model, make_engine, prefix_env):
    """The greedy parity matrix over the radix cache: (miss), (hit on a
    different suffix), (repeat hit) — every stream token-identical to the
    standalone path, with the hits aliasing the shared prefix's pages
    (hit_tokens counts the skipped prefill)."""
    prefix = [1, 2, 3, 4, 5, 6, 7, 8]          # 2 full pages
    px, py = prefix + [9, 10], prefix + [11]
    base_x = gpt_model.generate_tokens([px], BLOCK, 4, temperature=0.0)
    base_y = gpt_model.generate_tokens([py], BLOCK, 4, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, px, 4).result() == base_x   # miss
    assert _submit(engine, py, 4).result() == base_y   # hit (shared prefix)
    assert _submit(engine, px, 4).result() == base_x   # repeat hit
    pc = engine.stats()["prefix_cache"]
    assert pc["misses"] == 1 and pc["hits"] == 2, pc
    assert pc["hit_tokens"] == 16  # 2 pages x 4 tokens x 2 hits
    assert pc["hit_rate"] == pytest.approx(2 / 3)


def test_prefix_cache_eviction_then_rematch_parity(gpt_model, make_engine,
                                                   prefix_env):
    """Eviction correctness: churn distinct prefixes through a 4-page cache
    region until the first prefix is LRU-evicted, then resubmit it — the
    re-prefilled (and re-registered) stream is token-identical."""
    prefix_env.setenv("PENROZ_PREFIX_CACHE_PAGES", "4")
    pa = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 4, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, pa, 4).result() == base_a
    for j in range(3):  # 3 distinct 2-page prefixes overflow 4 pages
        p = [20 + j] * 8 + [j]
        base = gpt_model.generate_tokens([p], BLOCK, 3, temperature=0.0)
        assert _submit(engine, p, 3).result() == base
    pc = engine.stats()["prefix_cache"]
    assert pc["evicted_pages"] > 0, pc
    assert _submit(engine, pa, 4).result() == base_a  # evicted → recompute
    pc = engine.stats()["prefix_cache"]
    assert pc["capacity_pages"] == 4


def test_serving_stats_reports_prefix_and_chunk_fields(client, gpt_model,
                                                       prefix_env):
    """/serving_stats/ carries the new observability: prefix-cache hit
    rate + evictions and the prefill chunk-stall p99, per engine and
    aggregated (dashboard tile inputs), validated against the schema."""
    prefix_env.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    payload = _gen_payload(input=[[1, 2, 3, 4, 5, 6, 7, 8, 9]])
    status, first = _json(client, "POST", "/generate/", json=payload)
    assert status == 200
    status, second = _json(client, "POST", "/generate/", json=payload)
    assert status == 200
    assert second["tokens"] == first["tokens"]
    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200
    assert stats["prefix_cache_hit_rate"] == pytest.approx(0.5)
    assert stats["prefix_cache_evicted_pages"] == 0
    assert "prefill_chunk_stall_ms_p99" in stats
    engine = stats["engines"][0]
    assert engine["prefill_chunks"] >= 2
    assert engine["prefix_cache"]["hits"] == 1
    assert engine["prefix_cache"]["misses"] == 1
    assert engine["prefix_cache"]["hit_tokens"] == 8
    assert engine["prefill_max_chunks_between_steps"] <= 1


# -- fault tolerance: deadlines, backpressure, crash recovery (PR 3) --------

def _wait_tokens(collector, n, timeout=120):
    """Drain collector events until ``n`` tokens arrived (so the request is
    provably mid-decode)."""
    deadline = time.monotonic() + timeout
    while collector.received < n:
        assert time.monotonic() < deadline, "request never started decoding"
        try:
            kind, value = collector.q.get(timeout=1.0)
        except queue.Empty:
            continue
        assert kind == "token", kind
        collector.tokens.append(value)
        collector.received += 1


def test_step_crash_fails_all_cleanly_then_recovers_with_parity(
        gpt_model, make_engine, monkeypatch):
    """THE acceptance path: an injected decode.step crash fails every
    waiting request with a clean (typed) error, the engine fully resets
    its KV/prefix state, and the very next request completes with greedy
    output identical to the no-crash path."""
    from penroz_tpu.utils import faults
    pa, pb = [1, 2, 3], [5]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 6, temperature=0.0)
    monkeypatch.setenv(faults.ENV, "decode.step:raise@1")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    c1 = _submit(engine, pa, 6)
    c2 = _submit(engine, pb, 6)
    with pytest.raises(faults.InjectedFault):
        c1.result()
    with pytest.raises(faults.InjectedFault):
        c2.result()
    monkeypatch.delenv(faults.ENV)
    faults.reset()
    # next request: same engine object, post-reset state, token-identical
    assert _submit(engine, pa, 6).result() == base_a
    stats = engine.stats()
    assert stats["crashes_total"] == 1
    assert stats["engine_resets"] == 1
    assert stats["consecutive_crashes"] == 0  # success zeroed it
    assert stats["breaker_open"] is False
    assert engine.active_rows == 0


def test_prefill_chunk_crash_recovers_with_parity(gpt_model, make_engine,
                                                  monkeypatch):
    """Same recovery contract for the second tick site: a crash inside an
    admission prefill chunk."""
    from penroz_tpu.utils import faults
    prompt = [9, 10, 11, 12]
    base = gpt_model.generate_tokens([prompt], BLOCK, 5, temperature=0.0)
    monkeypatch.setenv(faults.ENV, "decode.prefill_chunk:raise@1")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    with pytest.raises(faults.InjectedFault):
        _submit(engine, prompt, 5).result()
    monkeypatch.delenv(faults.ENV)
    faults.reset()
    assert _submit(engine, prompt, 5).result() == base
    assert engine.stats()["engine_resets"] == 1


def test_queue_full_sheds_while_inflight_keeps_parity(gpt_model,
                                                      make_engine,
                                                      monkeypatch):
    """PENROZ_SCHED_MAX_QUEUE bounds admission: with the row busy and the
    queue full, submit raises QueueFullError immediately — and neither the
    in-flight nor the queued request's tokens change (no cross-request
    corruption under shedding)."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    pa, pb, pc = [1, 2, 3], [5], [7, 8]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 6, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
    monkeypatch.setenv(decode_scheduler.MAX_QUEUE_ENV, "1")
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@80")  # slow decode
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    ca = _submit(engine, pa, 6)
    _wait_tokens(ca, 1)          # A admitted: pending queue is empty
    cb = _submit(engine, pb, 4)  # queued (row busy) — fills the queue
    with pytest.raises(decode_scheduler.QueueFullError):
        _submit(engine, pc, 4)
    assert ca.result() == base_a
    assert cb.result() == base_b
    stats = engine.stats()
    assert stats["queue_rejections"] == 1
    assert stats["queue_wait_ms_p99"] is not None


def test_deadline_expires_while_queued(gpt_model, make_engine, monkeypatch):
    """A queued request whose deadline passes before a row frees is shed
    with a 'queued'-phase DeadlineExceeded — before any prefill — while
    the in-flight request keeps its exact stream."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    pa, pb = [1, 2, 3], [5]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@80")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    ca = _submit(engine, pa, 8)
    _wait_tokens(ca, 1)
    cb = _submit(engine, pb, 4, timeout_ms=150)
    with pytest.raises(decode_scheduler.DeadlineExceeded) as exc:
        cb.result()
    assert exc.value.phase == "queued"
    assert cb.received == 0      # shed before prefill ever ran
    assert ca.result() == base_a
    assert engine.stats()["deadline_timeouts"] == 1


def test_deadline_expires_in_flight_retires_at_boundary(gpt_model,
                                                        make_engine,
                                                        monkeypatch):
    """An in-flight deadline retires the row at the next step boundary:
    the tokens produced so far were delivered, then the stream ends with a
    timeout event — and the engine immediately serves the next request."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    prompt = [1, 2, 3]
    base = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@100")
    # Per-token deadline granularity is the n=1 contract: with supersteps
    # the sleep fires once per fused dispatch and the deadline is only
    # observed at block boundaries (covered by the dedicated superstep
    # deadline test below).
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "1")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    c = _submit(engine, prompt, 50, timeout_ms=350)
    with pytest.raises(decode_scheduler.DeadlineExceeded) as exc:
        c.result()
    assert exc.value.phase == "inflight"
    assert 1 <= c.received < 50
    assert engine.active_rows == 0
    assert _submit(engine, prompt, 4).result() == base
    assert engine.stats()["deadline_timeouts"] == 1


def test_circuit_breaker_opens_after_consecutive_crashes_then_probe_closes(
        gpt_model, make_engine, monkeypatch):
    """PENROZ_ENGINE_MAX_CRASHES consecutive crashes open the breaker:
    submits are refused with CircuitOpenError during the cooldown, then
    ONE probe request is admitted and its success closes the breaker."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    prompt = [1, 2, 3]
    base = gpt_model.generate_tokens([prompt], BLOCK, 5, temperature=0.0)
    monkeypatch.setenv(decode_scheduler.MAX_CRASHES_ENV, "2")
    monkeypatch.setenv(decode_scheduler.BREAKER_COOLDOWN_ENV, "400")
    monkeypatch.setenv(faults.ENV,
                       "decode.step:raise@1,decode.step:raise@2")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    with pytest.raises(faults.InjectedFault):
        _submit(engine, prompt, 5).result()          # crash 1
    assert engine.stats()["breaker_open"] is False
    assert engine.stats()["consecutive_crashes"] == 1
    with pytest.raises(faults.InjectedFault):
        _submit(engine, prompt, 5).result()          # crash 2 → breaker
    assert engine.stats()["breaker_open"] is True
    with pytest.raises(decode_scheduler.CircuitOpenError):
        _submit(engine, prompt, 5)                   # cooldown: refused
    time.sleep(0.5)                                  # cooldown elapses
    assert _submit(engine, prompt, 5).result() == base  # probe succeeds
    stats = engine.stats()
    assert stats["breaker_open"] is False            # probe closed it
    assert stats["consecutive_crashes"] == 0
    assert stats["crashes_total"] == 2
    assert stats["breaker_rejections"] == 1


def test_cancellation_frees_row_mid_flight(gpt_model, make_engine,
                                           monkeypatch):
    """req.cancelled (the client-disconnect signal) retires the row at the
    next boundary instead of decoding to max_new_tokens, and the slot
    serves the next request with exact parity."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    pa, pb = [1, 2, 3], [5]
    base_b = gpt_model.generate_tokens([pb], BLOCK, 5, temperature=0.0)
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@60")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    collector = _Collector(pa)
    req = decode_scheduler.Request(pa, 50, None, collector.on_event)
    engine.submit(req)
    _wait_tokens(collector, 2)
    req.cancelled = True
    deadline = time.monotonic() + 30
    while engine.active_rows and time.monotonic() < deadline:
        time.sleep(0.02)
    assert engine.active_rows == 0
    assert collector.received < 50   # provably did not run to completion
    assert _submit(engine, pb, 5).result() == base_b


def test_graceful_shutdown_drains_inflight_rows(gpt_model, make_engine,
                                                monkeypatch):
    """shutdown(drain_s=...) lets the in-flight request finish (every
    token delivered, done event sent) before the worker joins, and
    reports the successful join (returns True) — the satellite contract."""
    from penroz_tpu.utils import faults
    prompt = [1, 2, 3]
    base = gpt_model.generate_tokens([prompt], BLOCK, 6, temperature=0.0)
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@40")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    c = _submit(engine, prompt, 6)
    _wait_tokens(c, 1)
    assert engine.shutdown(timeout=30.0, drain_s=30.0) is True
    assert c.result(timeout=5) == base   # drained, not killed


def test_shutdown_reports_failed_join(gpt_model, make_engine, monkeypatch):
    """A worker thread that cannot join within the timeout is REPORTED
    (False + log) instead of silently leaked — satellite fix for the old
    fire-and-forget join."""
    from penroz_tpu.utils import faults
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@1500")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    c = _submit(engine, [1, 2], 2)
    _wait_tokens(c, 1)               # worker is now inside the slow step
    assert engine.shutdown(timeout=0.2) is False
    # the fixture's teardown shutdown() joins for real once the step ends


def test_max_stall_budget_runs_multiple_chunks(gpt_model, make_engine,
                                               monkeypatch):
    """PENROZ_SCHED_MAX_STALL_MS > 0 trades inter-token latency for
    admission speed: with a generous budget, several chunks run between
    decode steps (the default budget of 0 pins that at one)."""
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "1")
    monkeypatch.setenv("PENROZ_SCHED_MAX_STALL_MS", "60000")
    pa, pb = [5], [9, 10, 11, 12, 13, 14]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 8)
    deadline = time.monotonic() + 120
    while ca.received < 2:
        assert time.monotonic() < deadline, "A never started decoding"
        try:
            kind, value = ca.q.get(timeout=1.0)
        except queue.Empty:
            continue
        ca.tokens.append(value)
        ca.received += 1
    cb = _submit(engine, pb, 4)
    assert cb.result() == base_b
    assert ca.result() == base_a
    # all 6 of B's 1-token chunks fit one boundary under the huge budget
    assert engine.stats()["prefill_max_chunks_between_steps"] == 6


# -- fault tolerance over HTTP (429/504/503, lifecycle endpoints) ------------

def test_http_queue_full_429_with_retry_after(client, gpt_model,
                                              monkeypatch):
    """Queue-full sheds 429 + Retry-After while the in-flight and queued
    requests keep token-identical greedy outputs (the acceptance's
    no-corruption-under-shedding clause, end to end)."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    pa, pb = [1, 2, 3], [5]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(decode_scheduler.MAX_ROWS_ENV, "1")
    monkeypatch.setenv(decode_scheduler.MAX_QUEUE_ENV, "1")
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@80")
    test_client, loop = client

    async def go():
        task_a = asyncio.ensure_future(test_client.post(
            "/generate/", json=_gen_payload(input=[pa], max_new_tokens=8)))
        # wait until A occupies the row (pending queue empty again)
        for _ in range(200):
            stats = await (await test_client.get("/serving_stats/")).json()
            if stats["active_rows"] >= 1 and stats["queue_depth"] == 0:
                break
            await asyncio.sleep(0.02)
        else:
            raise AssertionError("A never admitted")
        task_b = asyncio.ensure_future(test_client.post(
            "/generate/", json=_gen_payload(input=[pb], max_new_tokens=4)))
        for _ in range(200):
            stats = await (await test_client.get("/serving_stats/")).json()
            if stats["queue_depth"] >= 1:
                break
            await asyncio.sleep(0.02)
        else:
            raise AssertionError("B never queued")
        resp_c = await test_client.post(
            "/generate/", json=_gen_payload(input=[[7, 8]],
                                            max_new_tokens=4))
        resp_a, resp_b = await task_a, await task_b
        return (resp_a.status, await resp_a.json(),
                resp_b.status, await resp_b.json(),
                resp_c.status, await resp_c.json(),
                resp_c.headers.get("Retry-After"))

    a_status, a_body, b_status, b_body, c_status, c_body, retry = \
        loop.run_until_complete(go())
    assert a_status == 200 and a_body["tokens"] == base_a
    assert b_status == 200 and b_body["tokens"] == base_b
    assert c_status == 429, c_body
    assert "overloaded" in c_body["detail"]
    assert retry is not None
    _, stats = _json(client, "GET", "/serving_stats/")
    assert stats["queue_rejections"] == 1


def test_http_deadline_504_queued_and_inflight(client, gpt_model,
                                               monkeypatch):
    """timeout_ms maps to 504 in both phases: shed from the queue while a
    slow request holds the row, and expired mid-flight afterwards — the
    concurrent in-flight request's tokens stay exact."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    pa = [1, 2, 3]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(decode_scheduler.MAX_ROWS_ENV, "1")
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@80")
    # Per-token deadline granularity is the n=1 contract (see the
    # superstep deadline test for the boundary-granularity behavior).
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "1")
    test_client, loop = client

    async def go():
        task_a = asyncio.ensure_future(test_client.post(
            "/generate/", json=_gen_payload(input=[pa], max_new_tokens=8)))
        for _ in range(200):
            stats = await (await test_client.get("/serving_stats/")).json()
            if stats["active_rows"] >= 1:
                break
            await asyncio.sleep(0.02)
        # queued-phase 504: B can't get the row within its 100ms budget
        resp_q = await test_client.post(
            "/generate/", json=_gen_payload(input=[[5]], max_new_tokens=4,
                                            timeout_ms=100))
        resp_a = await task_a
        # inflight-phase 504: row is free now; the deadline expires
        # mid-generation (slow steps, many tokens)
        resp_i = await test_client.post(
            "/generate/", json=_gen_payload(input=[[7]], max_new_tokens=14,
                                            timeout_ms=300))
        return (resp_q.status, await resp_q.json(), resp_a.status,
                await resp_a.json(), resp_i.status, await resp_i.json())

    q_status, q_body, a_status, a_body, i_status, i_body = \
        loop.run_until_complete(go())
    assert q_status == 504 and "queued" in q_body["detail"]
    assert a_status == 200 and a_body["tokens"] == base_a
    assert i_status == 504, i_body
    _, stats = _json(client, "GET", "/serving_stats/")
    assert stats["deadline_timeouts"] == 2


def test_http_stream_deadline_emits_timeout_line(client, gpt_model,
                                                 monkeypatch):
    """A streaming request whose deadline expires mid-flight delivers the
    tokens produced so far, then a literal 'timeout' line, then ends."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@100")
    # Per-token deadline granularity is the n=1 contract (see the
    # superstep deadline test for the boundary-granularity behavior).
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "1")
    test_client, loop = client

    async def go():
        resp = await test_client.post("/generate/", json=_gen_payload(
            input=[[1, 2]], max_new_tokens=13, stream=True, timeout_ms=350))
        assert resp.status == 200
        return (await resp.read()).decode()

    lines = loop.run_until_complete(go()).strip().split("\n")
    assert lines[-1] == "timeout"
    assert 1 <= len(lines) - 1 < 13
    assert all(line.isdigit() for line in lines[:-1])


def test_http_breaker_503_readyz_and_probe_recovery(client, gpt_model,
                                                    monkeypatch):
    """The breaker acceptance, end to end: N injected crashes → 503 from
    the scheduler path + /readyz not ready; after the cooldown one probe
    request succeeds with exact greedy parity and /readyz recovers."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    prompt = [1, 2, 3]
    base = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(decode_scheduler.MAX_CRASHES_ENV, "1")
    monkeypatch.setenv(decode_scheduler.BREAKER_COOLDOWN_ENV, "100000")
    monkeypatch.setenv(faults.ENV, "decode.step:raise@1")

    status, body = _json(client, "POST", "/generate/", json=_gen_payload())
    assert status == 500                     # the injected crash itself

    status, body = _json(client, "GET", "/readyz")
    assert status == 503
    assert body["ready"] is False
    assert body["breaker_open_engines"] == ["schedgpt"]
    status, _ = _json(client, "GET", "/healthz")
    assert status == 200                     # liveness unaffected

    status, body = _json(client, "POST", "/generate/", json=_gen_payload())
    assert status == 503                     # breaker sheds during cooldown
    assert "circuit breaker" in body["detail"]

    _, stats = _json(client, "GET", "/serving_stats/")
    assert stats["breaker_open"] is True
    assert stats["crashes_total"] == 1
    assert stats["engine_resets"] == 1

    # cooldown over (0ms), fault disarmed: the next request is the probe
    monkeypatch.setenv(decode_scheduler.BREAKER_COOLDOWN_ENV, "0")
    monkeypatch.delenv(faults.ENV)
    from penroz_tpu.utils import faults as _f
    _f.reset()
    status, body = _json(client, "POST", "/generate/", json=_gen_payload())
    assert status == 200
    assert body["tokens"] == base            # post-reset greedy parity
    status, body = _json(client, "GET", "/readyz")
    assert status == 200 and body["ready"] is True
    _, stats = _json(client, "GET", "/serving_stats/")
    assert stats["breaker_open"] is False


def test_http_breaker_fallback_to_legacy_path(client, gpt_model,
                                              monkeypatch):
    """PENROZ_SCHED_FALLBACK=1 degrades an open-breaker request to the
    pre-PR-1 single-sequence path (200 + exact tokens) instead of 503."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    base = gpt_model.generate_tokens([[1, 2, 3]], BLOCK, 4, temperature=0.0)
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(decode_scheduler.MAX_CRASHES_ENV, "1")
    monkeypatch.setenv(decode_scheduler.BREAKER_COOLDOWN_ENV, "100000")
    monkeypatch.setenv(faults.ENV, "decode.step:raise@1")
    status, _ = _json(client, "POST", "/generate/", json=_gen_payload())
    assert status == 500                     # crash opens the breaker
    monkeypatch.setenv(decode_scheduler.FALLBACK_ENV, "1")
    status, body = _json(client, "POST", "/generate/", json=_gen_payload())
    assert status == 200                     # degraded, not refused
    assert body["tokens"] == base
    _, stats = _json(client, "GET", "/serving_stats/")
    assert stats["breaker_open"] is True     # breaker itself stays open


def test_healthz_readyz_and_draining(client, workdir, monkeypatch):
    """Lifecycle endpoints: /healthz always 200; /readyz 200 when clean,
    503 while the scheduler registry is draining for shutdown."""
    from penroz_tpu.serve import decode_scheduler
    status, body = _json(client, "GET", "/healthz")
    assert status == 200 and body["status"] == "ok"
    status, body = _json(client, "GET", "/readyz")
    assert status == 200 and body["ready"] is True
    monkeypatch.setattr(decode_scheduler, "_DRAINING", True)
    status, body = _json(client, "GET", "/readyz")
    assert status == 503 and body["draining"] is True
    _, stats = _json(client, "GET", "/serving_stats/")
    assert stats["draining"] is True


# -- speculative decoding: prompt-lookup drafts + verify steps (PR 4) --------

REP_PROMPT = [1, 2, 3, 1, 2, 3, 1, 2]   # repetitive text: 2 pages of 4


@pytest.fixture
def spec_env(monkeypatch):
    """Spec decode on, with the aggressive 1-gram matcher so toy streams
    (which lock into short cycles) draft early."""
    monkeypatch.setenv("PENROZ_SPEC_DECODE", "1")
    monkeypatch.setenv("PENROZ_SPEC_NGRAM", "1")
    return monkeypatch


def _oracle_drafter(bases):
    """Draft the exact greedy continuation (from the precomputed standalone
    sequences) — deterministic full acceptance, so the verify/rollback
    path provably runs and multi-token emission is exercised."""
    def propose(history, k, n):
        for base in bases:
            if len(history) < len(base) and history == base[:len(history)]:
                return [int(t) for t in base[len(history):len(history) + k]]
        return []
    return propose


@pytest.mark.parametrize("paged_prefix,int8,chunk", [
    (paged, int8, chunk)
    for paged in (0, 1) for int8 in (0, 1) for chunk in ("16", "2")])
def test_spec_parity_matrix(gpt_model, make_engine, monkeypatch,
                            paged_prefix, int8, chunk):
    """THE acceptance matrix: greedy outputs with PENROZ_SPEC_DECODE=1 are
    token-identical to spec-off across prefix cache on/off, int8 KV
    on/off (all four cache variants) and chunked/one-shot prefill — with
    the verify path provably engaged (oracle drafts, full acceptance)."""
    from penroz_tpu.serve import spec_decode
    if paged_prefix:
        monkeypatch.setenv("PAGED_KV_CACHE", "1")
        monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    if int8:
        monkeypatch.setenv("TURBO_QUANT_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", chunk)
    monkeypatch.setenv("PENROZ_SPEC_DECODE", "1")
    # spec-off baseline: the legacy path under the same KV env flags
    base = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 6,
                                     temperature=0.0)
    monkeypatch.setattr(spec_decode, "propose", _oracle_drafter([base]))
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, REP_PROMPT, 6).result() == base
    # second request: a prefix-cache HIT when the cache is on
    assert _submit(engine, REP_PROMPT, 6).result() == base
    stats = engine.stats()
    assert stats["spec_decode"] is True
    assert stats["spec_verify_steps"] > 0
    assert stats["spec_drafted_tokens"] > 0
    assert stats["spec_accept_rate"] == 1.0          # oracle drafts
    assert stats["tokens_per_decode_step"] > 1.0
    if paged_prefix:
        assert stats["prefix_cache"]["hits"] >= 1


def test_spec_real_drafter_parity(gpt_model, make_engine, spec_env):
    """The real prompt-lookup drafter (no oracle): repetitive prompt +
    1-gram matching — parity is exact whatever the accept rate lands at,
    and drafting provably engaged on the toy stream's cycles."""
    prompt = [1, 2, 3, 1, 2]
    base = gpt_model.generate_tokens([prompt], BLOCK, 11, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, prompt, 11).result() == base
    stats = engine.stats()
    assert stats["spec_drafted_tokens"] > 0
    assert 0.0 <= stats["spec_accept_rate"] <= 1.0


def test_spec_adversarial_drafter_zero_accept_keeps_parity(
        gpt_model, make_engine, spec_env):
    """An always-wrong drafter costs accept rate, never correctness: every
    draft token is rejected (accept_rate == 0), each verify step's bonus
    token still advances the row, and the stream is token-identical."""
    from penroz_tpu.serve import spec_decode
    base = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 6,
                                     temperature=0.0)

    def wrong(history, k, n):
        nxt = base[len(history)] if len(history) < len(base) else 0
        return [(int(nxt) + 1) % 64] * min(k, 2)   # first token always wrong

    spec_env.setattr(spec_decode, "propose", wrong)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, REP_PROMPT, 6).result() == base
    stats = engine.stats()
    assert stats["spec_drafted_tokens"] > 0
    assert stats["spec_accepted_tokens"] == 0
    assert stats["spec_accept_rate"] == 0.0
    assert stats["tokens_per_decode_step"] == pytest.approx(1.0)


def test_spec_stop_token_inside_accepted_draft(gpt_model, make_engine,
                                               spec_env):
    """A stop token accepted mid-draft retires the row exactly where the
    plain path would: the tokens after the stop are discarded even though
    the verify step accepted them."""
    from penroz_tpu.serve import spec_decode
    base = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 8,
                                     temperature=0.0)
    stop = base[len(REP_PROMPT) + 2]               # third generated token
    base_stop = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 8,
                                          temperature=0.0, stop_token=stop)
    spec_env.setattr(spec_decode, "propose", _oracle_drafter([base]))
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, REP_PROMPT, 8, stop_token=stop).result() \
        == base_stop
    assert engine.stats()["spec_verify_steps"] > 0
    assert engine.active_rows == 0


def test_spec_mid_flight_admission_during_verify(gpt_model, make_engine,
                                                 spec_env):
    """A new row admitted while another row advances through verify steps:
    both keep their standalone streams (the newcomer prefills between
    ticks; the verifying row's rollbacks never touch other rows)."""
    from penroz_tpu.serve import spec_decode
    pa, pb = REP_PROMPT, [5, 6, 5, 6]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 7, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 5, temperature=0.0)
    spec_env.setattr(spec_decode, "propose",
                     _oracle_drafter([base_a, base_b]))
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 7)
    _wait_tokens(ca, 2)            # A provably mid-generation
    cb = _submit(engine, pb, 5)
    assert cb.result() == base_b
    assert ca.result() == base_a
    stats = engine.stats()
    assert stats["spec_verify_steps"] > 0
    assert stats["completed"] == 2


def test_spec_non_greedy_engine_bypasses_drafting(gpt_model, make_engine,
                                                  spec_env):
    """Non-greedy engines on the LEGACY (contiguous-cache phased) path
    still bypass drafting — its dispatch-order sampling keys would be
    perturbed by verify dispatches.  The unified ragged engine lifts the
    gate via positional-key rejection sampling
    (tests/test_pipeline_serving.py pins that parity); no PAGED_KV_CACHE
    here, so this engine is the phased one."""
    engine = make_engine("schedgpt", BLOCK, 0.8, 4, capacity=2)
    result = _submit(engine, [1, 2, 3], 4).result()
    assert len(result) == 7
    stats = engine.stats()
    assert stats["spec_decode"] is False
    assert stats["spec_drafted_tokens"] == 0
    assert stats["spec_verify_steps"] == 0


def _radix_nodes(cache):
    # walk every namespace root (adapter namespaces included)
    nodes, stack = [], [nd for root in cache._roots.values()
                        for nd in root.children.values()]
    while stack:
        nd = stack.pop()
        nodes.append(nd)
        stack.extend(nd.children.values())
    return nodes


def test_spec_verify_crash_recovers_with_parity(gpt_model, make_engine,
                                                spec_env, prefix_env):
    """Fault site decode.verify: a crash during a verify step fails the
    request cleanly, the engine reallocates its KV + prefix state
    (_alloc_state), and the next identical request is greedy-identical
    with no leaked paged blocks or pinned prefix pages."""
    from penroz_tpu.serve import spec_decode
    from penroz_tpu.utils import faults
    base = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 6,
                                     temperature=0.0)
    spec_env.setattr(spec_decode, "propose", _oracle_drafter([base]))
    spec_env.setenv(faults.ENV, "decode.verify:raise@1")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    with pytest.raises(faults.InjectedFault):
        _submit(engine, REP_PROMPT, 6).result()
    spec_env.delenv(faults.ENV)
    faults.reset()
    assert _submit(engine, REP_PROMPT, 6).result() == base
    stats = engine.stats()
    assert stats["crashes_total"] == 1
    assert stats["engine_resets"] == 1
    assert stats["breaker_open"] is False
    assert engine.active_rows == 0
    # no leaked pool state: every radix page accounted for, nothing pinned
    cache = engine._prefix_cache
    assert cache.free_pages + cache.cached_pages == cache.capacity_pages
    assert all(nd.refs == 0 for nd in _radix_nodes(cache))


def test_spec_http_serving_stats_and_streaming(client, gpt_model,
                                               monkeypatch):
    """End to end over HTTP: spec decode on the scheduler path keeps
    /generate/ token-identical (buffered + streaming), and
    /serving_stats/ carries the new spec fields through the schema."""
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    payload = _gen_payload(input=[[1, 2, 3, 1, 2]], max_new_tokens=9)
    status, legacy = _json(client, "POST", "/generate/", json=payload)
    assert status == 200
    monkeypatch.setenv("PENROZ_SPEC_DECODE", "1")
    monkeypatch.setenv("PENROZ_SPEC_NGRAM", "1")
    status, routed = _json(client, "POST", "/generate/", json=payload)
    assert status == 200
    assert routed["tokens"] == legacy["tokens"]

    test_client, loop = client

    async def go():
        resp = await test_client.post("/generate/",
                                      json=dict(payload, stream=True))
        assert resp.status == 200
        return (await resp.read()).decode()

    body = loop.run_until_complete(go())
    streamed = [int(line) for line in body.strip().split("\n")]
    assert streamed == legacy["tokens"][5:]

    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200
    assert stats["spec_decode_enabled"] is True
    assert stats["spec_drafted_tokens"] >= 0
    assert stats["tokens_per_decode_step"] >= 1.0
    engine = stats["engines"][0]
    assert engine["spec_decode"] is True
    assert "spec_accept_rate" in engine


# -- compiled multi-step decode: fused supersteps (PENROZ_SCHED_SUPERSTEP) ---


def _settled_stats(engine, timeout=30):
    """Engine stats once the worker loop has finished the tick that
    retired the last request: the 'done' event is delivered from inside
    the emit loop, BEFORE the tick's counter/timeline updates, so a
    reader racing the worker can see the pre-tick totals."""
    deadline = time.monotonic() + timeout
    stats = engine.stats()
    while time.monotonic() < deadline:
        time.sleep(0.05)
        nxt = engine.stats()
        if (engine.idle()
                and nxt["decode_tokens"] == stats["decode_tokens"]
                and nxt["dispatches_total"] == stats["dispatches_total"]
                and len(nxt["tick_timeline"]) == len(stats["tick_timeline"])):
            return nxt
        stats = nxt
    return stats


@pytest.mark.parametrize("superstep", [1, 4, 8])
@pytest.mark.parametrize("paged_prefix,int8,chunk", [
    (0, 0, "16"), (1, 0, "2"), (0, 1, "16"), (1, 1, "2")],
    ids=["fp-contig", "paged-prefix-chunked", "int8-contig",
         "int8-paged-prefix-chunked"])
def test_superstep_parity_matrix(gpt_model, make_engine, monkeypatch,
                                 superstep, paged_prefix, int8, chunk):
    """THE multi-step acceptance matrix: greedy outputs are
    token-identical across superstep ∈ {1, 4, 8} × prefix-cache on/off ×
    int8 KV on/off (all four cache variants) × chunked/one-shot prefill
    — two overlapping rows with different budgets, so rows provably
    finish (and keep compute-but-discarding) mid-block, plus a second
    wave for real prefix-cache hits in the 'on' combos."""
    from penroz_tpu.serve import decode_scheduler
    if paged_prefix:
        monkeypatch.setenv("PAGED_KV_CACHE", "1")
        monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    if int8:
        monkeypatch.setenv("TURBO_QUANT_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", chunk)
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, str(superstep))
    pa, pb = [1, 2, 3, 4, 5, 6, 7, 8], [5]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 6, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 9, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    ca, cb = _submit(engine, pa, 6), _submit(engine, pb, 9)
    assert ca.result() == base_a
    assert cb.result() == base_b
    # second wave: prefix-cache hit (when on) feeding straight into a
    # fused block
    assert _submit(engine, pa, 6).result() == base_a
    stats = _settled_stats(engine)
    assert stats["superstep"] == superstep
    assert stats["dispatches_total"] > 0
    if superstep > 1:
        # at least one dispatch actually fused >1 steps
        assert any(e["superstep"] > 1 for e in stats["tick_timeline"])
        assert stats["tokens_per_dispatch_avg"] > 1.0
    # fusing must not inflate the SPECULATION metric: a superstep counts
    # as N decode steps, so tokens/step stays bounded by the row count
    assert 1.0 <= stats["tokens_per_decode_step"] <= 2.0


def test_superstep_stop_token_detected_on_device(gpt_model, make_engine,
                                                 monkeypatch):
    """A stop token sampled mid-block deactivates the row ON DEVICE: the
    stream truncates exactly where the legacy per-token path stops
    (stop token delivered, nothing after it), and the row's slot
    recycles for the next request."""
    from penroz_tpu.serve import decode_scheduler
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "8")
    prompt = [1, 2, 3]
    base = gpt_model.generate_tokens([prompt], BLOCK, 12, temperature=0.0)
    stop = base[len(prompt) + 4]          # sampled mid-superstep
    base_stop = gpt_model.generate_tokens([prompt], BLOCK, 12,
                                          temperature=0.0, stop_token=stop)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    assert _submit(engine, prompt, 12, stop_token=stop).result() \
        == base_stop
    # slot recycles cleanly after the on-device early stop
    assert _submit(engine, prompt, 12).result() == base
    stats = _settled_stats(engine)
    assert stats["completed"] == 2
    assert any(e["superstep"] > 1 for e in stats["tick_timeline"])


def test_superstep_crash_mid_generation_recovers_with_parity(
        gpt_model, make_engine, monkeypatch):
    """decode.step:raise@2 with superstep 4 crashes the SECOND fused
    dispatch — the request is several supersteps deep when the scan's
    tick dies.  The waiting request fails cleanly, _alloc_state rebuilds
    the engine, and the resubmitted request is greedy-identical."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "4")
    prompt = [1, 2, 3]
    base = gpt_model.generate_tokens([prompt], BLOCK, 12, temperature=0.0)
    monkeypatch.setenv(faults.ENV, "decode.step:raise@2")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    c = _submit(engine, prompt, 12)
    with pytest.raises(faults.InjectedFault):
        c.result()
    # the crash landed mid-request: the first fused block (4 tokens) plus
    # the prefill token were already delivered, the rest never arrived
    assert 1 <= c.received < 12, c.received
    monkeypatch.delenv(faults.ENV)
    faults.reset()
    assert _submit(engine, prompt, 12).result() == base
    stats = engine.stats()
    assert stats["crashes_total"] == 1
    assert stats["engine_resets"] == 1
    assert engine.active_rows == 0


def test_superstep_deadline_retires_at_boundary(gpt_model, make_engine,
                                                monkeypatch):
    """A deadline expiring MID-superstep is only observed at the block
    boundary (the documented ≤N-token granularity trade): the row retires
    there with a timeout event and a 'timeout' trace retirement reason,
    and the engine serves the next request cleanly."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults, tracing
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "8")
    prompt = [1, 2, 3]
    base = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    # warm: compiles the prefill + superstep programs so the deadline below
    # measures the slow dispatch, not XLA
    _submit(engine, prompt, 12).result()
    # each fused dispatch now sleeps well past the deadline: the expiry
    # lands mid-block and must surface at the boundary
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@400")
    monkeypatch.setenv("PENROZ_TRACE_SAMPLE", "1")
    trace = tracing.maybe_trace("req-superstep-deadline")
    collector = _Collector(prompt)
    req = decode_scheduler.Request(prompt, 12, None, collector.on_event,
                                   timeout_ms=150,
                                   request_id="req-superstep-deadline",
                                   trace=trace)
    engine.submit(req)
    with pytest.raises(decode_scheduler.DeadlineExceeded) as exc:
        collector.result()
    assert exc.value.phase == "inflight"
    # tokens delivered before the boundary noticed the expiry — the
    # overshoot is bounded by one block, never the full budget
    assert 1 <= collector.received < 12
    assert trace.finished
    assert trace.meta.get("retire_reason") == "timeout"
    assert engine.stats()["deadline_timeouts"] == 1
    monkeypatch.delenv(faults.ENV)
    faults.reset()
    assert _submit(engine, prompt, 4).result() == base


def test_superstep_cancellation_observed_at_boundary(gpt_model,
                                                     make_engine,
                                                     monkeypatch):
    """req.cancelled flipped mid-superstep frees the row at the block
    boundary; the slot then serves the next request with exact parity."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "4")
    pa, pb = [1, 2, 3], [5]
    base_b = gpt_model.generate_tokens([pb], BLOCK, 5, temperature=0.0)
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@60")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    collector = _Collector(pa)
    req = decode_scheduler.Request(pa, 12, None, collector.on_event)
    engine.submit(req)
    _wait_tokens(collector, 1)
    req.cancelled = True
    deadline = time.monotonic() + 30
    while engine.active_rows and time.monotonic() < deadline:
        time.sleep(0.02)
    assert engine.active_rows == 0
    assert collector.received < 12
    assert _submit(engine, pb, 5).result() == base_b


def test_superstep_falls_back_while_admissions_pending(gpt_model,
                                                       make_engine,
                                                       monkeypatch):
    """A queued request must not wait N tokens for its slot: with the
    queue non-empty the planner falls back to n=1 ticks, so admission
    happens at the very next boundary (and the fused path resumes once
    the queue drains — both visible in the tick timeline)."""
    from penroz_tpu.serve import decode_scheduler
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "8")
    pa, pb = [1, 2, 3], [5]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 12, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 8, temperature=0.0)
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    ca = _submit(engine, pa, 12)
    cb = _submit(engine, pb, 8)   # queued behind A (capacity 1)
    assert ca.result() == base_a
    assert cb.result() == base_b
    timeline = _settled_stats(engine)["tick_timeline"]
    assert any(e["superstep"] == 1 for e in timeline)   # fallback ticks
    assert any(e["superstep"] > 1 for e in timeline)    # fused ticks


def test_superstep_dispatch_accounting(gpt_model, make_engine,
                                       monkeypatch):
    """The new dispatch metrics, exactly: prompt [1] + 12 tokens at
    superstep 8 is one prefill token + supersteps of 8, 2 and a single
    step (pow-2-bucketed tail) — 3 decode dispatches for 11 decode
    tokens, with the histogram-backed tokens_per_dispatch reflecting the
    fused blocks and tokens_per_decode_step pinned at 1.0 (fusing is not
    speculation)."""
    from penroz_tpu.serve import decode_scheduler
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "8")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    _submit(engine, [1], 12).result()
    stats = _settled_stats(engine)
    assert stats["dispatches_total"] == 3
    assert stats["decode_tokens"] == 11     # 12 minus the prefill token
    assert stats["decode_steps"] == 11
    assert stats["tokens_per_decode_step"] == pytest.approx(1.0)
    assert stats["tokens_per_dispatch_avg"] == pytest.approx(11 / 3, abs=1e-3)
    assert stats["tokens_per_dispatch_p50"] == pytest.approx(2.0)
    supersteps = [e["superstep"] for e in stats["tick_timeline"]
                  if e["superstep"] > 0]
    assert sorted(supersteps) == [1, 2, 8]


def test_idle_engine_parks_on_condvar_no_spin(gpt_model, make_engine):
    """An idle engine burns no CPU: the worker loop parks on the
    condition variable (untimed wait) after its last request, so neither
    the loop counter nor the tick telemetry advances while idle — the
    old 1s-timeout poll would have woken it repeatedly."""
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=1)
    _submit(engine, [1, 2], 3).result()
    time.sleep(0.1)                      # let the loop finish its pass
    loops0 = engine._loops
    ticks0 = len(engine._tick_timeline)
    steps0 = engine.stats()["decode_steps"]
    time.sleep(1.5)                      # > the old poll interval
    assert engine._loops == loops0       # zero wakeups while idle
    assert len(engine._tick_timeline) == ticks0
    assert engine.stats()["decode_steps"] == steps0
    # and the parked engine still wakes instantly for new work
    assert engine.idle()
    _submit(engine, [1, 2], 3).result(timeout=30)


def test_step_rng_fold_in_jit_matches_host_fold(gpt_model):
    """The hoisted sampler-key advance is bit-identical: folding the
    dispatch ordinal into the base key INSIDE the jitted step (the new
    path) samples exactly the tokens the old host-side fold produced —
    seeded non-greedy output is unchanged by the hoist."""
    import jax
    from penroz_tpu.ops import kv_cache as KV
    model = gpt_model

    def fresh_kv():
        return (KV.create_kv_state(model.arch.kv_specs, 2, BLOCK,
                                   model._kv_dtype())
                .with_static_table()
                .with_lengths(np.zeros(2, np.int32)))

    toks = np.array([[3], [5]], np.int32)
    lengths = np.array([1, 1], np.int32)
    rng = jax.random.key(7)
    old, _ = model.decode_step_batched(fresh_kv(), toks, lengths,
                                       jax.random.fold_in(rng, 5),
                                       temperature=1.0)
    new, _ = model.decode_step_batched(fresh_kv(), toks, lengths, rng,
                                       temperature=1.0, dispatch=5)
    assert np.array_equal(np.asarray(old), np.asarray(new))


def test_non_greedy_seeded_output_invariant_under_superstep(
        gpt_model, make_engine, monkeypatch):
    """Sequential single-row NON-greedy traffic samples the identical
    token sequence at superstep 1 and 8: each fused step consumes the
    same dispatch ordinal (hence the same folded key) the single-step
    loop would have, so fusing never perturbs seeded sampling."""
    from penroz_tpu.serve import decode_scheduler
    prompt = [1, 2, 3]
    outs = {}
    for superstep in (1, 8):
        monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, str(superstep))
        engine = make_engine("schedgpt", BLOCK, 1.0, None, capacity=2)
        outs[superstep] = [
            _submit(engine, prompt, 10).result(),
            _submit(engine, [5], 6).result(),
        ]
        engine.shutdown()
    assert outs[1] == outs[8]


# -- ragged unified prefill+decode ticks (one mixed dispatch per tick) -------


UNIFIED_MATRIX = [
    # (prefix, int8, superstep, spec, chunk) — an L8-style cover: every
    # axis hits both values and the heavy pairings (int8×fused,
    # prefix×spec, spec×chunked) all appear at least once.
    (0, 0, "1", 0, "16"),
    (1, 0, "8", 1, "2"),
    (0, 1, "8", 0, "2"),
    (1, 1, "1", 1, "16"),
    (1, 1, "8", 0, "16"),
    (0, 0, "8", 1, "16"),
    (1, 0, "1", 0, "2"),
    (0, 1, "1", 1, "2"),
]


@pytest.mark.parametrize("prefix,int8,superstep,spec,chunk", UNIFIED_MATRIX)
def test_unified_parity_matrix(gpt_model, make_engine, monkeypatch,
                               prefix, int8, superstep, spec, chunk):
    """THE unified-tick acceptance matrix: with the paged cache on, the
    ragged one-dispatch scheduler returns greedy tokens identical to the
    legacy phased scheduler AND to the standalone legacy path — across
    prefix cache, int8 KV, superstep {1,8}, spec decode (oracle drafts)
    and chunked/one-shot prefill, with two overlapping rows per run so
    the dispatch is genuinely mixed."""
    from penroz_tpu.serve import decode_scheduler, spec_decode
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    if prefix:
        monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "16")
    if int8:
        monkeypatch.setenv("TURBO_QUANT_KV_CACHE", "1")
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, superstep)
    monkeypatch.setenv(decode_scheduler.PREFILL_CHUNK_ENV, chunk)
    pa, pb = REP_PROMPT, [5, 6, 5, 6]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 6, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 6, temperature=0.0)
    if spec:
        monkeypatch.setenv("PENROZ_SPEC_DECODE", "1")
        monkeypatch.setattr(spec_decode, "propose",
                            _oracle_drafter([base_a, base_b]))
    for ragged in ("1", "0"):
        monkeypatch.setenv(decode_scheduler.RAGGED_ENV, ragged)
        engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
        ca = _submit(engine, pa, 6)
        cb = _submit(engine, pb, 6)
        assert ca.result() == base_a, f"row A diverged (ragged={ragged})"
        assert cb.result() == base_b, f"row B diverged (ragged={ragged})"
        stats = engine.stats()
        unified_ticks = [e for e in stats["tick_timeline"]
                         if e.get("unified")]
        if ragged == "1":
            assert unified_ticks, "paged engine must take the unified path"
        else:
            assert not unified_ticks, \
                "PENROZ_RAGGED_ATTENTION=0 must restore phased ticks"
        if spec:
            assert stats["spec_verify_steps"] > 0
            assert stats["spec_accept_rate"] == 1.0
        engine.shutdown()


def test_unified_tick_fuses_chunks_and_drafts(gpt_model, make_engine,
                                              monkeypatch):
    """Superstep-fallback removal, asserted from the tick timeline: a
    unified tick holding BOTH pending prefill chunks and a spec-verify
    span still dispatches a fused block (superstep > 1).  The legacy
    scheduler dropped to single-step whenever either was present; the
    ragged dispatch has no such fallback."""
    from penroz_tpu.serve import decode_scheduler, spec_decode
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "8")
    monkeypatch.setenv(decode_scheduler.PREFILL_CHUNK_ENV, "2")
    monkeypatch.setenv("PENROZ_SPEC_DECODE", "1")
    monkeypatch.setenv("PENROZ_SPEC_K", "2")
    pa, pb = [1, 2], [1, 2, 3, 1, 2, 3, 1, 2, 3, 1, 2, 3]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
    monkeypatch.setattr(spec_decode, "propose",
                        _oracle_drafter([base_a, base_b]))
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 8)
    # wait until row A is decoding (first token out) before admitting the
    # long chunked prompt, so some later tick plans A's verify span
    # alongside B's prefill chunks
    deadline = time.monotonic() + 60
    while ca.q.qsize() == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert ca.q.qsize() > 0, "row A produced no token within 60s"
    cb = _submit(engine, pb, 4)
    assert ca.result() == base_a
    assert cb.result() == base_b
    fused_mixed = [e for e in engine.stats()["tick_timeline"]
                   if e.get("unified") and e["prefill_chunks"] > 0
                   and e["verify_rows"] > 0 and e["superstep"] > 1]
    assert fused_mixed, \
        "no tick fused prefill chunks with a verify span at superstep > 1"


def test_unified_compile_budget(gpt_model, make_engine, monkeypatch):
    """Compile-churn guard end to end: 50 requests with varied prompt and
    output lengths through the unified path compile a bounded mixed-step
    program set — descriptor-count buckets (pow-2, utils/bucketing.py)
    times step-count buckets {1,2,4,8}, never a program per shape."""
    from penroz_tpu.serve import decode_scheduler
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "8")
    monkeypatch.setenv(decode_scheduler.PREFILL_CHUNK_ENV, "4")
    engine = make_engine("schedgpt", BLOCK, 0.0, None, capacity=4)
    rng = np.random.default_rng(42)
    pending = []
    for i in range(50):
        plen = int(rng.integers(2, 11))
        max_new = int(rng.integers(1, min(6, BLOCK - plen)))
        prompt = [int(t) for t in rng.integers(1, 9, size=plen)]
        pending.append(_submit(engine, prompt, max_new))
        if len(pending) >= 8:
            pending.pop(0).result()
    for collector in pending:
        collector.result()
    counts = engine.jit_program_counts()
    assert counts.get("mixed_step", 0) >= 1, \
        "the unified path never dispatched"
    # n ∈ {1,2,4,8} step buckets × NB ∈ {1,2,4,8} descriptor buckets
    # = 16 is the pow-2 ceiling for this workload (an unbucketed planner
    # would compile a program per distinct (plen, max_new, rows) shape —
    # dozens); the exact subset reached depends on admission timing
    assert counts["mixed_step"] <= 16, \
        f"mixed-step program churn: {counts['mixed_step']} programs"
