"""Replica-group router tests (serve/router.py).

Tier-1-safe: CPU, small shapes, no `slow` marker.  Three contracts carry
the weight here: (1) greedy parity — routing a request through any number
of replicas returns exactly the tokens the legacy single-engine path
returns; (2) failover — one breaker-tripped replica never surfaces a
client-visible 503 while a healthy sibling exists, and the half-open
probe re-admits it afterwards; (3) affinity — a repeated page-aligned
prefix family is steered to the replica whose prefix cache holds the
pages.
"""

import queue
import time

import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

# CI tier: heavier compiles (serving stack), same tier as test_app.
pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}


@pytest.fixture(autouse=True)
def _router_registry(workdir):
    """Fresh engine+router registries and fault/QoS counters per test."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import decode_scheduler, qos
    from penroz_tpu.utils import faults
    faults.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()
    yield
    decode_scheduler.reset()
    faults.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    """A serialized toy GPT (attention + KV cache on the decode path)."""
    model = NeuralNetworkModel("schedgpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


class _Collector:
    def __init__(self, prompt):
        self.q = queue.Queue()
        self.tokens = list(prompt)

    def on_event(self, kind, value):
        self.q.put((kind, value))

    def result(self, timeout=180):
        deadline = time.monotonic() + timeout
        while True:
            kind, value = self.q.get(
                timeout=max(deadline - time.monotonic(), 0.1))
            if kind == "token":
                self.tokens.append(value)
            elif kind == "done":
                return self.tokens
            else:
                raise value


def _submit(router, prompt, max_new):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt)
    router.submit(decode_scheduler.Request(prompt, max_new, None,
                                           collector.on_event))
    return collector


def _get_router(monkeypatch, n=2):
    """The production seam: get_engine hands back a router when
    PENROZ_SCHED_REPLICAS > 1."""
    from penroz_tpu.serve import decode_scheduler, router
    monkeypatch.setenv(decode_scheduler.REPLICAS_ENV, str(n))
    engine = decode_scheduler.get_engine("schedgpt", BLOCK, 0.0, None)
    assert isinstance(engine, router.EngineRouter)
    assert len(engine.replicas) == n
    return engine


def test_router_failover_then_probe_readmission(gpt_model, monkeypatch):
    """Breaker trips on replica 0 → requests reroute to replica 1 with no
    client-visible refusal; after the cooldown the half-open probe goes to
    replica 0 first and its success re-admits it."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    prompt = [1, 2, 3]
    base = gpt_model.generate_tokens([prompt], BLOCK, 5, temperature=0.0)
    monkeypatch.setenv(decode_scheduler.MAX_CRASHES_ENV, "2")
    monkeypatch.setenv(decode_scheduler.BREAKER_COOLDOWN_ENV, "100000")
    monkeypatch.setenv(faults.ENV,
                       "decode.step:raise@1,decode.step:raise@2")
    router = _get_router(monkeypatch, n=2)
    # Idle group → deterministic tie-break: both crashes land on replica 0.
    with pytest.raises(faults.InjectedFault):
        _submit(router, prompt, 5).result()
    with pytest.raises(faults.InjectedFault):
        _submit(router, prompt, 5).result()
    r0, r1 = router.replicas
    assert r0.stats()["breaker_open"] is True
    # One open replica must NOT mark the model not-ready: a healthy
    # sibling still serves.
    assert "schedgpt" not in decode_scheduler.breaker_open_engines()
    # Reroute: submissions succeed on replica 1, no CircuitOpenError.
    for _ in range(2):
        assert _submit(router, prompt, 5).result() == base
    assert r1.stats()["completed"] == 2
    assert r0.stats()["completed"] == 0
    # Cooldown over (0ms): probes outrank healthy replicas, so the next
    # admission IS the probe.
    monkeypatch.setenv(decode_scheduler.BREAKER_COOLDOWN_ENV, "0")
    assert _submit(router, prompt, 5).result() == base
    s0 = r0.stats()
    assert s0["completed"] == 1          # the probe ran on replica 0
    assert s0["breaker_open"] is False   # and closed the breaker
    assert s0["consecutive_crashes"] == 0


def test_router_all_replicas_open_surfaces_circuit_error(gpt_model,
                                                         monkeypatch):
    """Only when EVERY replica's breaker is open does the client see
    CircuitOpenError — and only then is the model listed not-ready."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    prompt = [1, 2, 3]
    monkeypatch.setenv(decode_scheduler.MAX_CRASHES_ENV, "1")
    monkeypatch.setenv(decode_scheduler.BREAKER_COOLDOWN_ENV, "100000")
    monkeypatch.setenv(faults.ENV,
                       "decode.step:raise@1,decode.step:raise@2")
    router = _get_router(monkeypatch, n=2)
    with pytest.raises(faults.InjectedFault):
        _submit(router, prompt, 5).result()      # replica 0 opens
    assert decode_scheduler.breaker_open_engines() == []
    with pytest.raises(faults.InjectedFault):
        _submit(router, prompt, 5).result()      # replica 1 opens
    assert decode_scheduler.breaker_open_engines() == ["schedgpt"]
    with pytest.raises(decode_scheduler.CircuitOpenError):
        _submit(router, prompt, 5)


# single-replica arms ride the slow lane (tier1_budget): a 1-replica
# router is engine passthrough (the scheduler parity matrix pins it);
# both 2-replica arms keep every real routing seam fast
@pytest.mark.parametrize("replicas,affinity", [
    pytest.param(1, "1", marks=pytest.mark.slow),
    (2, "1"), (2, "0")])
@pytest.mark.parametrize("prefix", [False, True])
@pytest.mark.parametrize("superstep", ["1", "8"])
def test_router_greedy_parity_matrix(gpt_model, monkeypatch, replicas,
                                     affinity, prefix, superstep):
    """Token parity through the router under {1 replica, 2 affinity-on,
    2 affinity-off} × prefix-cache × superstep, with the 1-device serving
    mesh active throughout."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.serve import router as router_mod
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, superstep)
    monkeypatch.setenv(router_mod.AFFINITY_ENV, affinity)
    monkeypatch.setenv("PENROZ_SERVE_MESH", "1")
    if prefix:
        monkeypatch.setenv("PAGED_KV_CACHE", "1")
        monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    # A page-aligned shared-prefix pair plus a disjoint prompt: exercises
    # steering (when on) and cold placement in the same run.
    prompts = [[1, 2, 3, 4, 5, 6, 7, 8],
               [1, 2, 3, 4, 5, 6, 7, 8, 9],
               [11, 12]]
    bases = [gpt_model.generate_tokens([p], BLOCK, 5, temperature=0.0)
             for p in prompts]
    monkeypatch.setenv(decode_scheduler.REPLICAS_ENV, str(replicas))
    engine = decode_scheduler.get_engine("schedgpt", BLOCK, 0.0, None)
    if replicas > 1:
        assert isinstance(engine, router_mod.EngineRouter)
    collectors = [_submit(engine, p, 5) for p in prompts]
    for collector, base in zip(collectors, bases):
        assert collector.result() == base
    stats = decode_scheduler.serving_stats()
    assert stats["router_replicas"] == (replicas if replicas > 1 else 0)


def test_router_prefix_affinity_steers_family_to_one_replica(gpt_model,
                                                             monkeypatch):
    """A repeated-prefix family (same two leading pages, different tails)
    lands on the replica that cached those pages: first request is the
    cold miss, every later one an affinity hit on the same replica."""
    from penroz_tpu.serve import decode_scheduler
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    router = _get_router(monkeypatch, n=2)
    shared = [1, 2, 3, 4, 5, 6, 7, 8]          # two full pages
    family = [shared + tail for tail in ([9], [10, 11], [12], [13])]
    bases = [gpt_model.generate_tokens([p], BLOCK, 5, temperature=0.0)
             for p in family]
    for prompt, base in zip(family, bases):
        assert _submit(router, prompt, 5).result() == base
    assert router.affinity_misses == 1          # the cold first request
    assert router.affinity_hits == len(family) - 1
    done = [e.stats()["completed"] for e in router.replicas]
    assert sorted(done) == [0, len(family)]     # whole family, one replica
    stats = decode_scheduler.serving_stats()
    assert stats["router_affinity_hits"] == len(family) - 1
    assert stats["router_affinity_misses"] == 1
    assert stats["router_affinity_hit_rate"] == pytest.approx(0.75)


# ---------------------------------------------------------------------------
# Disaggregated prefill (PENROZ_DISAGG_PREFILL=1)
# ---------------------------------------------------------------------------

def _disagg_env(monkeypatch, prefill_replicas="1", prefix=True):
    from penroz_tpu.serve import router as router_mod
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    if prefix:
        monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    monkeypatch.setenv("PENROZ_MEMLEDGER_STRICT", "1")
    monkeypatch.setenv(router_mod.DISAGG_ENV, "1")
    monkeypatch.setenv(router_mod.DISAGG_REPLICAS_ENV, prefill_replicas)


def _assert_no_transit_or_blob_leaks():
    """Strict partition check after a disagg run: every page owned, no
    lingering transit attribution, no staged blob left on shm."""
    import glob
    import os
    from penroz_tpu.serve import memledger
    from penroz_tpu.utils import checkpoint
    mem = memledger.memory_stats()
    for entry in mem["engines"]:
        pools = entry["pool_pages"]
        assert pools.get("transit", 0) == 0, pools
        assert sum(pools.values()) == entry["pool_pages_total"]
    blobs = glob.glob(os.path.join(checkpoint.SHM_PATH, "**", "pageblob_*"),
                      recursive=True)
    assert blobs == [], blobs


@pytest.mark.parametrize("transport", ["d2d", "host"])
# int8 KV parity through the hand-off is pinned by the single-engine
# matrices and the int8 codec property tests
@pytest.mark.parametrize("int8", [False,
                                  pytest.param(True, marks=pytest.mark.slow)])
@pytest.mark.parametrize("prefix", [False, True])
@pytest.mark.parametrize("superstep", ["1", "8"])
def test_router_disagg_greedy_parity_matrix(gpt_model, monkeypatch, int8,
                                            prefix, superstep, transport):
    """Tentpole acceptance: disaggregated prefill is token-identical to the
    legacy single-engine path across int8 KV × prefix-cache × superstep ×
    hand-off transport (d2d device arrays / host-staged blob) — and every
    request provably travelled the export → import seam (no silent
    monolithic fallback)."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.serve import router as router_mod
    _disagg_env(monkeypatch, prefix=prefix)
    monkeypatch.setenv(decode_scheduler.DISAGG_TRANSPORT_ENV, transport)
    if int8:
        monkeypatch.setenv("TURBO_QUANT_KV_CACHE", "1")
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, superstep)
    prompts = [[1, 2, 3, 4, 5, 6, 7, 8],
               [1, 2, 3, 4, 5, 6, 7, 8, 9],
               [11, 12]]
    # legacy baseline under the same KV env flags
    bases = [gpt_model.generate_tokens([p], BLOCK, 5, temperature=0.0)
             for p in prompts]
    router = _get_router(monkeypatch, n=2)
    assert [e.role for e in router.replicas] == ["prefill", "decode"]
    collectors = [_submit(router, p, 5) for p in prompts]
    for collector, base in zip(collectors, bases):
        assert collector.result() == base
    per = [e.stats() for e in router.replicas]
    assert sum(p["disagg_exports"] for p in per) == len(prompts)
    assert sum(p["disagg_imports"] for p in per) == len(prompts)
    assert sum(p["disagg_handoff_failures"] for p in per) == 0
    # prefill replicas never decode: every emitted token is the decode
    # replica's (the first token ships inside the hand-off)
    assert per[0]["completed"] == 0
    assert per[1]["completed"] == len(prompts)
    stats = decode_scheduler.serving_stats()
    assert stats["disagg_prefill_replicas"] == 1
    assert stats["disagg_exports"] == len(prompts)
    assert stats["disagg_imports"] == len(prompts)
    assert stats["disagg_handoff_ms_p99"] is not None
    assert stats["disagg_transport"] == transport
    assert [e["role"] for e in stats["engines"]] == ["prefill", "decode"]
    assert all(e["disagg_transport"] == transport
               for e in stats["engines"])
    _assert_no_transit_or_blob_leaks()


def test_router_disagg_off_keeps_flat_routing(gpt_model, monkeypatch):
    """PENROZ_DISAGG_PREFILL=0 (or unset) leaves the PR 14 flat group:
    every replica role 'decode', no sinks installed, zero disagg counters
    in /serving_stats/."""
    from penroz_tpu.serve import decode_scheduler
    router = _get_router(monkeypatch, n=2)
    assert [e.role for e in router.replicas] == ["decode", "decode"]
    assert all(e._handoff_sink is None for e in router.replicas)
    assert router.disagg is False
    base = gpt_model.generate_tokens([[1, 2, 3]], BLOCK, 4, temperature=0.0)
    assert _submit(router, [1, 2, 3], 4).result() == base
    stats = decode_scheduler.serving_stats()
    assert stats["disagg_prefill_replicas"] == 0
    assert stats["disagg_exports"] == 0
    assert stats["disagg_imports"] == 0


@pytest.mark.parametrize("ordinal,phase", [(1, "export"), (2, "import")])
def test_router_disagg_handoff_failure_falls_back_with_parity(
        gpt_model, monkeypatch, ordinal, phase):
    """disagg.handoff crash mid-export (@1) or mid-import (@2): the request
    falls back to monolithic prefill on a decode replica, output is
    greedy-identical, the failure is counted, and neither a transit page
    nor a staged blob outlives the hand-off."""
    from penroz_tpu.utils import faults
    _disagg_env(monkeypatch)
    monkeypatch.setenv(faults.ENV, f"disagg.handoff:raise@{ordinal}")
    prompt = [1, 2, 3, 4, 5, 6, 7]
    base = gpt_model.generate_tokens([prompt], BLOCK, 5, temperature=0.0)
    router = _get_router(monkeypatch, n=2)
    assert _submit(router, prompt, 5).result() == base
    per = [e.stats() for e in router.replicas]
    assert sum(p["disagg_handoff_failures"] for p in per) == 1, phase
    assert sum(p["disagg_imports"] for p in per) == 0
    # the decode replica ran the request whole either way
    assert per[1]["completed"] == 1
    _assert_no_transit_or_blob_leaks()


def test_router_disagg_drain_finishes_inflight_export(gpt_model,
                                                      monkeypatch):
    """Draining a prefill replica lets its in-flight export complete
    before the worker stops: the hand-off lands on the decode replica and
    the client sees the full greedy output, not an error."""
    import time as time_mod
    from penroz_tpu.utils import faults
    _disagg_env(monkeypatch)
    # widen the export window so the drain provably overlaps it
    monkeypatch.setenv(faults.ENV, "disagg.handoff:sleep@300")
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    base = gpt_model.generate_tokens([prompt], BLOCK, 5, temperature=0.0)
    router = _get_router(monkeypatch, n=2)
    r0, r1 = router.replicas
    collector = _submit(router, prompt, 5)
    deadline = time_mod.monotonic() + 120
    while r0.active_rows == 0 and time_mod.monotonic() < deadline:
        time_mod.sleep(0.002)
    assert r0.active_rows == 1          # prefill (or export) in flight
    assert r0.shutdown(timeout=60, drain_s=60) is True
    assert r0.stats()["disagg_exports"] == 1
    assert collector.result() == base
    assert r1.stats()["disagg_imports"] == 1


@pytest.mark.parametrize("ordinal,phase", [(1, "export"), (2, "import")])
def test_router_disagg_d2d_fault_falls_back_to_host_transport(
        gpt_model, monkeypatch, ordinal, phase):
    """disagg.d2d transport failure at either end — the exporter's device
    gather (@1) or the importer's re-shard+scatter (@2, which refuses the
    hand-off back so the exporter re-sends from its parked source pages) —
    falls back to the host-staged blob codec FOR THAT HAND-OFF: greedy
    parity, the import still lands, and neither a transit page nor a
    staged blob outlives the request."""
    from penroz_tpu.utils import faults
    _disagg_env(monkeypatch)
    monkeypatch.setenv(faults.ENV, f"disagg.d2d:raise@{ordinal}")
    prompt = [1, 2, 3, 4, 5, 6, 7]
    base = gpt_model.generate_tokens([prompt], BLOCK, 5, temperature=0.0)
    router = _get_router(monkeypatch, n=2)
    assert _submit(router, prompt, 5).result() == base
    per = [e.stats() for e in router.replicas]
    assert sum(p["disagg_imports"] for p in per) == 1, phase
    assert sum(p["disagg_handoff_failures"] for p in per) == 1, phase
    # the hand-off ultimately shipped host-side and decoded remotely
    assert per[0]["completed"] == 0 and per[1]["completed"] == 1
    _assert_no_transit_or_blob_leaks()


def test_router_disagg_d2d_midstream_fallback_parity(gpt_model,
                                                     monkeypatch):
    """Acceptance: a d2d failure in the MIDDLE of a hand-off stream
    downgrades only THAT hand-off to the host codec — its neighbours stay
    d2d, every output is greedy-identical, and nothing leaks."""
    from penroz_tpu.utils import faults
    _disagg_env(monkeypatch)
    # Sequential submits make the site ordinals deterministic: calls 1+2
    # are hand-off A's export+import, call 3 is hand-off B's exporter-side
    # device gather (fails -> host re-stage, no importer d2d call), calls
    # 4+5 are hand-off C back on the fast path.
    monkeypatch.setenv(faults.ENV, "disagg.d2d:raise@3")
    prompts = [[1, 2, 3, 4, 5], [6, 7, 8], [9, 10, 11, 12]]
    bases = [gpt_model.generate_tokens([p], BLOCK, 5, temperature=0.0)
             for p in prompts]
    router = _get_router(monkeypatch, n=2)
    for prompt, base in zip(prompts, bases):
        assert _submit(router, prompt, 5).result() == base
    per = [e.stats() for e in router.replicas]
    assert sum(p["disagg_exports"] for p in per) == len(prompts)
    assert sum(p["disagg_imports"] for p in per) == len(prompts)
    assert sum(p["disagg_handoff_failures"] for p in per) == 1
    _assert_no_transit_or_blob_leaks()


# ---------------------------------------------------------------------------
# Elastic roles (PENROZ_DISAGG_ELASTIC=1)
# ---------------------------------------------------------------------------

def _wait_for_roles(router, want, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if sorted(e.role for e in router.replicas) == sorted(want):
            return
        time.sleep(0.005)
    raise AssertionError([e.role for e in router.replicas])


def test_router_affinity_stale_role_entry_ages_out(gpt_model, monkeypatch):
    """Affinity-index hygiene satellite: a fingerprint entry pointing at a
    replica that has since flipped to prefill-role is deleted on lookup
    (outcome="stale_role") instead of steering decode traffic at it — the
    repeat prompt still completes, on a replica that actually decodes."""
    from penroz_tpu.serve import metrics as serve_metrics
    _disagg_env(monkeypatch)
    router = _get_router(monkeypatch, n=3)
    assert [e.role for e in router.replicas] == \
        ["prefill", "decode", "decode"]
    shared = [1, 2, 3, 4, 5, 6, 7, 8]        # two full pages
    base = gpt_model.generate_tokens([shared + [9]], BLOCK, 5,
                                     temperature=0.0)
    assert _submit(router, shared + [9], 5).result() == base
    with router._lock:
        warm_idx = set(router._affinity.values())
    assert warm_idx and all(i in (1, 2) for i in warm_idx)
    victim = router.replicas[min(warm_idx)]
    victim_done = victim.stats()["completed"]
    victim.request_role("prefill")           # the elastic flip, applied by
    _wait_for_roles(router, ["prefill", "prefill", "decode"])  # the worker
    before = serve_metrics.ROUTER_AFFINITY.value(outcome="stale_role")
    assert _submit(router, shared + [10], 5).result() == \
        gpt_model.generate_tokens([shared + [10]], BLOCK, 5, temperature=0.0)
    assert router.affinity_stale_roles >= 1
    assert serve_metrics.ROUTER_AFFINITY.value(outcome="stale_role") > before
    with router._lock:                        # the index self-cleaned
        assert victim.replica not in set(router._affinity.values())
    # the repeat prompt decoded elsewhere — the stale target got nothing
    assert victim.stats()["completed"] == victim_done
    _assert_no_transit_or_blob_leaks()


def test_router_elastic_shrink_flips_idle_prefill_to_decode(gpt_model,
                                                            monkeypatch):
    """Elastic rebalance, shrink direction: with the backlog/occupancy
    ratio parked below PENROZ_DISAGG_REBALANCE_DOWN, the submit-path
    rebalancer asks the emptiest prefill replica to flip to decode; the
    engine applies it at a drain boundary, the counters record it, and the
    cached router survives the drifted role vector (no rebuild)."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.serve import metrics as serve_metrics
    from penroz_tpu.serve import router as router_mod
    _disagg_env(monkeypatch, prefill_replicas="2")
    monkeypatch.setenv(router_mod.DISAGG_ELASTIC_ENV, "1")
    monkeypatch.setenv(router_mod.REBALANCE_COOLDOWN_ENV, "0")
    monkeypatch.setenv(router_mod.REBALANCE_DOWN_ENV, "1000000000")
    router = _get_router(monkeypatch, n=3)
    assert [e.role for e in router.replicas] == \
        ["prefill", "prefill", "decode"]
    before = serve_metrics.DISAGG_ROLE_CHANGES.value()
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    base = gpt_model.generate_tokens([prompt], BLOCK, 5, temperature=0.0)
    assert _submit(router, prompt, 5).result() == base
    assert router.role_changes_requested >= 1
    _wait_for_roles(router, ["prefill", "decode", "decode"])
    stats = decode_scheduler.serving_stats()
    assert stats["disagg_role_changes"] >= 1
    assert serve_metrics.DISAGG_ROLE_CHANGES.value() > before
    # PENROZ_DISAGG_PREFILL_MIN floor: never flips the last prefill away
    assert "prefill" in [e.role for e in router.replicas]
    assert decode_scheduler.get_engine("schedgpt", BLOCK, 0.0, None) \
        is router
    _assert_no_transit_or_blob_leaks()


def test_engine_role_flip_chaos_retries_and_audits_clean(gpt_model,
                                                         monkeypatch):
    """disagg.rebalance crash mid-flip: the fault fires BEFORE the
    mutation, so the role registry stays consistent through crash
    recovery, the strict ledger audit is green, and the flip retries at
    the next drain boundary (grow direction, at the engine seam)."""
    from penroz_tpu.serve import metrics as serve_metrics
    from penroz_tpu.utils import faults
    _disagg_env(monkeypatch)
    monkeypatch.setenv(faults.ENV, "disagg.rebalance:raise@1")
    router = _get_router(monkeypatch, n=2)
    r0, r1 = router.replicas
    assert [r0.role, r1.role] == ["prefill", "decode"]
    before = serve_metrics.DISAGG_ROLE_CHANGES.value()
    r1.request_role("prefill")
    _wait_for_roles(router, ["prefill", "prefill"])
    assert r1.stats()["disagg_role_changes"] == 1
    assert serve_metrics.DISAGG_ROLE_CHANGES.value() == before + 1
    assert r1._requested_role is None
    _assert_no_transit_or_blob_leaks()
    r1.request_role("decode")                # restore the startup split
    _wait_for_roles(router, ["prefill", "decode"])


def test_router_disagg_prefill_breakers_open_decode_serves_monolithic(
        gpt_model, monkeypatch):
    """All prefill replicas breaker-open: /readyz stays ready (a healthy
    decode replica can serve the request whole) and submissions complete
    monolithically on the decode replica with greedy parity."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    _disagg_env(monkeypatch)
    monkeypatch.setenv(decode_scheduler.MAX_CRASHES_ENV, "2")
    monkeypatch.setenv(decode_scheduler.BREAKER_COOLDOWN_ENV, "100000")
    monkeypatch.setenv(faults.ENV,
                       "decode.prefill_chunk:raise@1,"
                       "decode.prefill_chunk:raise@2")
    prompt = [1, 2, 3, 4, 5]
    base = gpt_model.generate_tokens([prompt], BLOCK, 5, temperature=0.0)
    router = _get_router(monkeypatch, n=2)
    r0, r1 = router.replicas
    assert r0.role == "prefill"
    # phase steering sends both doomed prefills to the prefill replica
    for _ in range(2):
        with pytest.raises(faults.InjectedFault):
            _submit(router, prompt, 5).result()
    assert r0.stats()["breaker_open"] is True
    # every prefill replica open but decode healthy → still ready
    assert "schedgpt" not in decode_scheduler.breaker_open_engines()
    assert _submit(router, prompt, 5).result() == base
    s1 = r1.stats()
    assert s1["completed"] == 1
    assert s1["disagg_imports"] == 0    # monolithic, not an import
    assert r0.stats()["completed"] == 0


def test_router_disagg_scoring_counts_queued_prefill_tokens():
    """Satellite: least-loaded placement ranks by queued prompt TOKENS of
    the request's class before queue depth — a replica holding two
    100-token prompts is more loaded than one holding five 3-token
    prompts, which depth-based scoring would get backwards."""
    import threading
    from penroz_tpu.serve import decode_scheduler, qos
    from penroz_tpu.serve import router as router_mod

    class _FakeEngine:
        def __init__(self, replica):
            self.replica = replica
            self.role = "decode"
            self._shutdown = False
            self._draining = False
            self._breaker_open = False
            self._probe_inflight = False
            self._breaker_open_t = 0.0
            self._cond = threading.Condition()
            self._pending = qos.WFQueue()
            self.active_rows = 0

    def _req(n_tokens):
        return decode_scheduler.Request(list(range(1, n_tokens + 1)), 1,
                                        None, lambda *a: None)

    router = object.__new__(router_mod.EngineRouter)
    router.replicas = [_FakeEngine(0), _FakeEngine(1)]
    router.disagg = False
    few_huge, many_tiny = router.replicas
    for _ in range(2):
        few_huge._pending.push(_req(100))     # depth 2, 200 tokens
    for _ in range(5):
        many_tiny._pending.push(_req(3))      # depth 5, 15 tokens
    order = router._candidates(_req(4), target=None)
    assert [e.replica for e in order] == [1, 0]


def test_router_replicas_visible_in_stats_and_memory(gpt_model,
                                                     monkeypatch):
    """Replica engines surface individually in /serving_stats/ and the
    memledger /memory/ view, tagged with their replica index, and each
    reports its own partition-invariant pool."""
    from penroz_tpu.serve import decode_scheduler, memledger
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    router = _get_router(monkeypatch, n=2)
    base = gpt_model.generate_tokens([[1, 2, 3]], BLOCK, 4, temperature=0.0)
    assert _submit(router, [1, 2, 3], 4).result() == base
    engines = decode_scheduler.serving_stats()["engines"]
    assert [(e["replica"], e["mesh_devices"]) for e in engines] == \
        [(0, 1), (1, 1)]
    mem = memledger.memory_stats()
    assert [e["replica"] for e in mem["engines"]] == [0, 1]
    for entry in mem["engines"]:
        pools = entry["pool_pages"]
        assert sum(pools.values()) == entry["pool_pages_total"]


# ---------------------------------------------------------------------------
# Hibernated-session placement (serve/tierstore.py, PR 17)
# ---------------------------------------------------------------------------

def _session_env(monkeypatch, tmp_path):
    from penroz_tpu.serve import tierstore
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    monkeypatch.setenv("PENROZ_TIER_DISK_PATH", str(tmp_path / "tier"))
    tierstore.reset()


def _submit_session(router, prompt, max_new, session_id):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt)
    router.submit(decode_scheduler.Request(prompt, max_new, None,
                                           collector.on_event,
                                           session_id=session_id))
    return collector


def _wait_tier(sid, tier, timeout=60):
    from penroz_tpu.serve import tierstore
    deadline = time.monotonic() + timeout
    while True:
        rec = tierstore.TIERS.get(sid)
        if rec is not None and rec.tier == tier:
            return rec
        assert time.monotonic() < deadline, \
            f"session {sid} never reached tier {tier!r}: {rec}"
        time.sleep(0.02)


def test_router_session_steer_to_home_replica(gpt_model, monkeypatch,
                                              tmp_path):
    """A wake prompt whose affinity entries are gone (LRU churn) still
    lands on the replica that hibernated the session: the tier store's
    placement record steers it home (outcome="session_steer"), where the
    radix copy makes the wake HBM-fast."""
    from penroz_tpu.serve import metrics as serve_metrics
    from penroz_tpu.serve import tierstore
    _session_env(monkeypatch, tmp_path)
    router = _get_router(monkeypatch, n=2)
    prompt = [1, 2, 3, 4, 5, 6, 7]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [9]
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)
    assert _submit_session(router, prompt, 4, "conv").result() == out
    rec = _wait_tier("conv", "host")
    home = int(rec.replica)
    done_before = router.replicas[home].stats()["completed"]
    with router._lock:          # simulate affinity-index LRU churn
        router._affinity.clear()
    before = serve_metrics.ROUTER_AFFINITY.value(outcome="session_steer")
    assert _submit(router, cont, 3).result() == base
    assert router.session_steers == 1
    assert router.session_redirects == 0
    assert serve_metrics.ROUTER_AFFINITY.value(outcome="session_steer") \
        == before + 1
    assert router.replicas[home].stats()["completed"] == done_before + 1
    assert tierstore.TIERS.promotions[("hbm", "ok")] == 1  # radix-fast wake


def test_router_session_redirect_when_home_breaker_open(gpt_model,
                                                        monkeypatch,
                                                        tmp_path):
    """A hibernated session whose home replica is breaker-open wakes on a
    healthy sibling (outcome="session_redirect") via the process-wide
    host tier — and the record survives to steer home again after the
    breaker closes."""
    from penroz_tpu.serve import metrics as serve_metrics
    from penroz_tpu.serve import tierstore
    _session_env(monkeypatch, tmp_path)
    router = _get_router(monkeypatch, n=2)
    prompt = [1, 2, 3, 4, 5, 6, 7]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [9]
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)
    assert _submit_session(router, prompt, 4, "conv").result() == out
    rec = _wait_tier("conv", "host")
    home = int(rec.replica)
    other = 1 - home
    router.replicas[home]._breaker_open = True
    router.replicas[home]._breaker_open_t = time.monotonic()
    with router._lock:
        router._affinity.clear()
    assert _submit(router, cont, 3).result() == base
    assert router.session_redirects == 1
    assert serve_metrics.ROUTER_AFFINITY.value(outcome="session_redirect") \
        >= 1
    assert router.replicas[other].stats()["completed"] == 1
    # blob import on the sibling, not an HBM alias on the dead home
    assert tierstore.TIERS.promotions[("host", "ok")] == 1
    # the record was NOT dropped: once the home recovers, steering resumes
    router.replicas[home]._breaker_open = False
    assert tierstore.TIERS.get("conv") is not None
    with router._lock:
        router._affinity.clear()
    assert _submit(router, cont, 3).result() == base
    assert router.session_steers == 1


def test_router_session_placement_survives_role_flip(gpt_model,
                                                     monkeypatch,
                                                     tmp_path):
    """Affinity-hygiene satellite: unlike prefix-affinity entries (which
    age out on a stale role), a hibernated session's placement record
    survives its home replica flipping to prefill-role — wakes redirect
    to a decode sibling while flipped, then steer home again after the
    replica flips back."""
    from penroz_tpu.serve import tierstore
    _disagg_env(monkeypatch)
    monkeypatch.setenv("PENROZ_TIER_DISK_PATH", str(tmp_path / "tier"))
    tierstore.reset()
    router = _get_router(monkeypatch, n=3)
    assert [e.role for e in router.replicas] == \
        ["prefill", "decode", "decode"]
    prompt = [1, 2, 3, 4, 5, 6, 7]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [9]
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)
    assert _submit_session(router, prompt, 4, "conv").result() == out
    rec = _wait_tier("conv", "host")
    home = int(rec.replica)
    assert router.replicas[home].role == "decode"   # retired on decode
    router.replicas[home].request_role("prefill")   # elastic flip
    deadline = time.monotonic() + 60
    while router.replicas[home].role != "prefill":
        assert time.monotonic() < deadline
        time.sleep(0.005)
    with router._lock:
        router._affinity.clear()
    assert _submit(router, cont, 3).result() == base
    assert router.session_redirects == 1
    assert tierstore.TIERS.get("conv") is not None  # record survived
    router.replicas[home].request_role("decode")    # flip back
    while router.replicas[home].role != "decode":
        assert time.monotonic() < deadline
        time.sleep(0.005)
    with router._lock:
        router._affinity.clear()
    assert _submit(router, cont, 3).result() == base
    assert router.session_steers == 1               # home again
    _assert_no_transit_or_blob_leaks()
