"""Hierarchical KV tiering + session hibernation tests (serve/tierstore.py).

Two layers:

* TierStore unit tests — registration/match/placement semantics, tenant
  quotas, host→disk spill and disk-cap LRU drops, corrupt-blob policy —
  driven with synthetic numpy blobs, no engine.
* Engine/API tests — the load-bearing parity contract: a session
  hibernated at retirement and resumed from each tier (HBM radix alias,
  host blob import on a different engine, disk blob import after a
  ``decode_scheduler.reset()``) streams exactly the tokens the same
  history produces cold, across int8 × superstep; corrupt blobs recompute
  instead of crashing or mis-serving; the memledger ``hibernating`` state
  balances under strict audits; both fault sites crash-recover.
"""

import queue
import time

import numpy as np
import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}


@pytest.fixture(autouse=True)
def _tier_registry(workdir, tmp_path, monkeypatch):
    """Fresh engine registry + tier store + fault/quota state per test;
    the disk tier writes under this test's tmp dir, never shared shm."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import decode_scheduler, qos, tierstore
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_TIER_DISK_PATH", str(tmp_path / "tier"))
    faults.reset()
    qos.reset()
    tierstore.reset()
    KV.reset_unpin_underflow_count()
    yield
    decode_scheduler.reset()
    tierstore.reset()
    faults.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()


# -- TierStore unit layer ----------------------------------------------------

def _register(store, sid, tokens, *, tenant="default", model_id="m",
              stamp=7, page_size=4, nbytes=1024, owner=1, replica="r0",
              quantized=False):
    return store.register(
        sid, tenant=tenant, model_id=model_id, model_stamp=stamp,
        tokens=tuple(tokens), kv_len=(len(tokens) // page_size) * page_size,
        page_size=page_size, quantized=quantized, nbytes=nbytes,
        owner=owner, replica=replica)


def _blob(pages=2, page_size=4, quantized=False):
    """A synthetic export_pages-shaped blob (one layer, tiny planes)."""
    plane = np.zeros((1, pages * page_size, 2), dtype=np.float32)
    return {"page_size": page_size, "pages": pages,
            "length": pages * page_size, "quantized": quantized,
            "k": [plane], "v": [plane.copy()]}


def test_register_match_depth_and_token_verification():
    """match() returns the DEEPEST whole-page-verified session, caps the
    usable span at len(tokens)-1, and never aliases on a token mismatch
    even when fingerprints would collide on a prefix."""
    from penroz_tpu.serve.tierstore import TierStore
    store = TierStore()
    assert _register(store, "s1", range(8))          # 2 pages: [0..7]
    assert _register(store, "s2", range(12))         # 3 pages: [0..11]
    # 13 tokens agree with s2 for all 3 pages (12 < 13 usable)
    rec, depth = store.match(list(range(13)), model_id="m", model_stamp=7,
                             page_size=4, quantized=False)
    assert rec.session_id == "s2" and depth == 3
    # exactly 12 tokens: one must remain to sample, so only 2 pages usable
    rec, depth = store.match(list(range(12)), model_id="m", model_stamp=7,
                             page_size=4, quantized=False)
    assert depth == 2
    # diverges inside page 2 -> only the first page may alias
    rec, depth = store.match([0, 1, 2, 3, 99, 98, 97, 96, 8], model_id="m",
                             model_stamp=7, page_size=4, quantized=False)
    assert rec is not None and depth == 1
    # wrong pool layout or model: no match
    assert store.match(list(range(13)), model_id="m", model_stamp=7,
                       page_size=4, quantized=True) == (None, 0)
    assert store.match(list(range(13)), model_id="other", model_stamp=7,
                       page_size=4, quantized=False) == (None, 0)


def test_match_stale_model_stamp_drops_session():
    """A session hibernated under superseded weights is dropped at match
    time (stale KV is never served) and counted as a stale promotion."""
    from penroz_tpu.serve.tierstore import TierStore
    store = TierStore()
    assert _register(store, "s1", range(8), stamp=7)
    rec, depth = store.match(list(range(9)), model_id="m", model_stamp=8,
                             page_size=4, quantized=False)
    assert (rec, depth) == (None, 0)
    assert store.resident_sessions() == 0
    assert store.promotions[("hbm", "stale")] == 1
    assert store.drops["stale_model"] == 1


def test_reregister_replaces_and_drop_owner_spares_lower_tiers():
    """Re-registering a session id supersedes the old record; drop_owner
    only reaps tier-"hbm" records (host/disk blobs left HBM already)."""
    from penroz_tpu.serve.tierstore import TierStore
    store = TierStore()
    assert _register(store, "s1", range(8), owner=1)
    assert _register(store, "s1", range(12), owner=1)   # multi-turn update
    assert store.resident_sessions() == 1
    assert store.drops["replaced"] == 1
    assert store.get("s1").kv_len == 12
    assert _register(store, "s2", range(4), owner=1)
    assert store.demote_to_host("s2", _blob(1))
    assert store.get("s2").tier == "host"
    assert store.drop_owner(1, "engine_reset") == 1     # only s1 (hbm)
    assert store.get("s1") is None
    assert store.get("s2").tier == "host"


def test_tenant_tier_quota_evicts_lru_then_refuses(monkeypatch):
    """PENROZ_QOS_TENANT_TIER_MB: a hibernation over cap evicts that
    tenant's LRU sessions first; one that can never fit is refused; other
    tenants' residency is untouched."""
    from penroz_tpu.serve.tierstore import TierStore
    monkeypatch.setenv("PENROZ_QOS_TENANT_TIER_MB", "0.002")  # 2000 bytes
    store = TierStore()
    assert _register(store, "a1", range(8), tenant="acme", nbytes=900)
    assert _register(store, "a2", range(4), tenant="acme", nbytes=900)
    assert _register(store, "b1", range(4), tenant="beta", nbytes=900)
    # 900 more puts acme at 2700 > 2000: a1 (LRU) is evicted
    assert _register(store, "a3", [50, 51, 52, 53], tenant="acme",
                     nbytes=900)
    assert store.get("a1") is None
    assert store.drops["quota"] == 1
    assert {r["session_id"] for r in store.list_sessions()} \
        == {"a2", "b1", "a3"}
    # a session larger than the whole cap is refused outright
    assert not _register(store, "a4", range(4), tenant="acme", nbytes=3000)
    assert store.drops["quota_refused"] == 1
    assert store.get("a2") is not None   # refusal evicted nothing


def test_host_cap_spills_lru_to_disk_and_disk_cap_drops(monkeypatch,
                                                        tmp_path):
    """Host-cap overflow spills LRU host blobs into the CRC disk store
    (files appear under PENROZ_TIER_DISK_PATH); disk-cap overflow drops
    LRU disk sessions, blob files included."""
    from penroz_tpu.serve.tierstore import TierStore
    from penroz_tpu.utils import checkpoint
    store = TierStore()
    blob_bytes = checkpoint.page_blob_nbytes(_blob(2))
    assert blob_bytes > 0
    # host cap fits exactly one blob
    monkeypatch.setenv("PENROZ_TIER_HOST_MB", str(blob_bytes / 1e6))
    for i, sid in enumerate(("s1", "s2", "s3")):
        assert _register(store, sid, range(i * 8, i * 8 + 8))
        assert store.demote_to_host(sid, _blob(2))
    # s3 is the only host resident; s1, s2 spilled LRU-first to disk
    tiers = {r["session_id"]: r["tier"] for r in store.list_sessions()}
    assert tiers == {"s1": "disk", "s2": "disk", "s3": "host"}
    assert store.demotions["host"] == 3 and store.demotions["disk"] == 2
    assert checkpoint.tier_blob_nbytes("s1") > 0
    stats = store.stats()
    assert stats["tier_bytes"]["host_tier"] == blob_bytes
    assert stats["tier_bytes"]["disk_tier"] \
        == checkpoint.tier_blob_nbytes("s1") * 2
    # shrink the disk cap to one stored blob: s1 (LRU) is dropped fully
    monkeypatch.setenv("PENROZ_TIER_DISK_MB",
                       str(checkpoint.tier_blob_nbytes("s1") / 1e6))
    assert _register(store, "s4", range(40, 48))
    assert store.demote_to_host("s4", _blob(2))
    assert store.get("s1") is None
    assert store.drops["disk_cap"] >= 1
    assert checkpoint.tier_blob_nbytes("s1") == 0   # file reclaimed


def test_corrupt_and_missing_disk_blobs_are_misses(monkeypatch):
    """A disk blob that fails CRC is a miss + corrupt counter (record
    dropped, file reclaimed); a vanished file is a plain miss. fetch()
    never raises — the admission recomputes."""
    import os
    from penroz_tpu.serve.tierstore import TierStore
    from penroz_tpu.utils import checkpoint
    monkeypatch.setenv("PENROZ_TIER_HOST_MB", "0")  # straight to disk
    store = TierStore()
    for sid in ("sc", "sm"):
        assert _register(store, sid, range(8) if sid == "sc"
                         else range(8, 16))
        assert store.demote_to_host(sid, _blob(2))
        assert store.get(sid).tier == "disk"
    path = checkpoint.tier_blob_path("sc")
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF                       # bit-flip the payload
    with open(path, "wb") as f:
        f.write(raw)
    assert store.fetch("sc") is None
    assert store.corrupt_blobs == 1
    assert store.promotions[("disk", "corrupt")] == 1
    assert store.get("sc") is None and not os.path.exists(path)
    os.remove(checkpoint.tier_blob_path("sm"))       # blob vanished
    assert store.fetch("sm") is None
    assert store.promotions[("disk", "miss")] == 1
    assert store.corrupt_blobs == 1                  # not corrupt, missing
    # truncation corrupts too (container header/CRC can't validate)
    assert _register(store, "st", range(16, 24))
    assert store.demote_to_host("st", _blob(2))
    path = checkpoint.tier_blob_path("st")
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:len(raw) // 2])
    assert store.fetch("st") is None
    assert store.corrupt_blobs == 2


def test_placement_is_side_effect_free_and_quant_agnostic():
    """placement() (the router's steering probe) finds a session without
    touching LRU order or any counter, and matches across the quantized
    pool-layout variants the router cannot see."""
    from penroz_tpu.serve.tierstore import TierStore
    store = TierStore()
    assert _register(store, "s1", range(8), quantized=True)
    assert _register(store, "s2", range(20, 28))
    before_order = list(store._sessions)
    before_promos = dict(store.promotions)
    rec = store.placement(list(range(9)), model_id="m", page_size=4)
    assert rec is not None and rec.session_id == "s1"
    assert list(store._sessions) == before_order     # no LRU touch
    assert dict(store.promotions) == before_promos   # no counters
    assert store.placement([7, 7, 7, 7, 7], model_id="m",
                           page_size=4) is None
    # match() (the engine-side path) DOES touch LRU
    store.match(list(range(9)), model_id="m", model_stamp=7, page_size=4,
                quantized=True)
    assert list(store._sessions)[-1] == "s1"


# -- engine / API layer ------------------------------------------------------

@pytest.fixture
def tier_env(monkeypatch):
    """Paged pool + radix cache sized for BLOCK=16 toy prompts, strict
    memledger audits on every transition."""
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    monkeypatch.setenv("PENROZ_MEMLEDGER_STRICT", "1")
    return monkeypatch


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("tiergpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def make_engine():
    from penroz_tpu.serve import decode_scheduler
    engines = []

    def build(*args, **kwargs):
        engine = decode_scheduler.DecodeEngine(*args, **kwargs)
        engines.append(engine)
        return engine

    yield build
    for engine in engines:
        engine.shutdown()


class _Collector:
    def __init__(self, prompt):
        self.q = queue.Queue()
        self.tokens = list(prompt)

    def on_event(self, kind, value):
        self.q.put((kind, value))

    def result(self, timeout=180):
        deadline = time.monotonic() + timeout
        while True:
            kind, value = self.q.get(
                timeout=max(deadline - time.monotonic(), 0.1))
            if kind == "token":
                self.tokens.append(value)
            elif kind == "done":
                return self.tokens
            else:
                raise value


def _submit(engine, prompt, max_new, session_id=None):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt)
    engine.submit(decode_scheduler.Request(prompt, max_new, None,
                                           collector.on_event,
                                           session_id=session_id))
    return collector


def _wait_tier(sid, tier, timeout=60):
    """Demotion is async (worker-loop tail) — poll the store."""
    from penroz_tpu.serve import tierstore
    deadline = time.monotonic() + timeout
    while True:
        rec = tierstore.TIERS.get(sid)
        if rec is not None and rec.tier == tier:
            return rec
        assert time.monotonic() < deadline, \
            f"session {sid} never reached tier {tier!r}: {rec}"
        time.sleep(0.02)


@pytest.mark.parametrize("int8,superstep", [
    # fp step-1 rides the slow lane too (tier1_budget): the int8-step8
    # diagonal keeps hibernate/resume parity fast
    pytest.param(0, 1, marks=pytest.mark.slow),
    pytest.param(0, 8, marks=pytest.mark.slow),  # step8 covered by int8-step8
    pytest.param(1, 1, marks=pytest.mark.slow),  # int8 covered at step8
    (1, 8)],
    ids=["fp-step1", "fp-step8", "int8-step1", "int8-step8"])
def test_hibernate_resume_parity_matrix(gpt_model, make_engine, tier_env,
                                        int8, superstep):
    """THE tiering acceptance matrix: a session hibernated at retirement
    resumes token-identically from (a) the still-resident radix copy and
    (b) the host blob on a FRESH engine after ``decode_scheduler.reset()``
    dropped the radix pages — across int8 KV and superstep sizes."""
    from penroz_tpu.serve import decode_scheduler, tierstore
    if int8:
        tier_env.setenv("TURBO_QUANT_KV_CACHE", "1")
    tier_env.setenv("PENROZ_SCHED_SUPERSTEP", str(superstep))
    prompt = [1, 2, 3, 4, 5, 6, 7]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [9]                       # next turn extends the history
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)

    engine = make_engine("tiergpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, prompt, 4, session_id="conv").result() == out
    _wait_tier("conv", "host")
    # (a) HBM-fast wake: radix copy still resident on the live engine
    assert _submit(engine, cont, 3).result() == base
    stats = engine.stats()
    assert stats["sessions_hibernated"] >= 1
    # no blob import — the radix copy served the wake
    assert stats["session_promotions"] == 0
    assert tierstore.TIERS.promotions[("hbm", "ok")] == 1

    # (b) host-blob wake on a brand-new engine (old pool is gone)
    decode_scheduler.reset()
    engine2 = make_engine("tiergpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine2, cont, 3).result() == base
    assert engine2.stats()["session_promotions"] == 1
    assert tierstore.TIERS.promotions[("host", "ok")] == 1


def test_cross_replica_wake_without_session_id(gpt_model, make_engine,
                                               tier_env):
    """Promotion is content-addressed: a session hibernated on replica A
    wakes on replica B from the shared host tier — no session_id on the
    resume request, radix caches not shared."""
    from penroz_tpu.serve import tierstore
    prompt = [3, 1, 4, 1, 5]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [2]
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)
    a = make_engine("tiergpt", BLOCK, 0.0, None, capacity=2, replica=0)
    b = make_engine("tiergpt", BLOCK, 0.0, None, capacity=2, replica=1)
    assert _submit(a, prompt, 4, session_id="nomad").result() == out
    rec = _wait_tier("nomad", "host")
    assert rec.replica == 0
    assert _submit(b, cont, 3).result() == base
    assert b.stats()["session_promotions"] == 1
    assert tierstore.TIERS.promotions[("host", "ok")] == 1
    assert a.stats()["session_promotions"] == 0


def test_disk_wake_survives_engine_reset(gpt_model, make_engine, tier_env):
    """With a zero host cap the demotion spills straight to disk; the blob
    outlives ``decode_scheduler.reset()`` and resumes with parity."""
    from penroz_tpu.serve import decode_scheduler, tierstore
    tier_env.setenv("PENROZ_TIER_HOST_MB", "0")
    prompt = [9, 10, 11, 12, 13]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [7]
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)
    engine = make_engine("tiergpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, prompt, 4, session_id="frozen").result() == out
    _wait_tier("frozen", "disk")
    decode_scheduler.reset()
    engine2 = make_engine("tiergpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine2, cont, 3).result() == base
    assert tierstore.TIERS.promotions[("disk", "ok")] == 1
    assert tierstore.TIERS.stats()["tier_demotions"]["disk"] == 1


def test_corrupt_disk_blob_recomputes_never_missserves(gpt_model,
                                                       make_engine,
                                                       tier_env):
    """Satellite: a bit-flipped disk blob yields the SAME tokens via
    recompute — a miss plus ``penroz_tier_corrupt_blobs_total``, never a
    crash or a wrong stream."""
    from penroz_tpu.serve import decode_scheduler, tierstore
    from penroz_tpu.utils import checkpoint
    tier_env.setenv("PENROZ_TIER_HOST_MB", "0")
    prompt = [5, 4, 3, 2, 1]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [6]
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)
    engine = make_engine("tiergpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, prompt, 4, session_id="bitrot").result() == out
    _wait_tier("bitrot", "disk")
    path = checkpoint.tier_blob_path("bitrot")
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(raw)
    decode_scheduler.reset()
    engine2 = make_engine("tiergpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine2, cont, 3).result() == base   # recomputed
    assert tierstore.TIERS.corrupt_blobs == 1
    assert tierstore.TIERS.promotions[("disk", "corrupt")] == 1
    assert tierstore.TIERS.get("bitrot") is None
    assert engine2.stats()["crashes_total"] == 0


def test_memledger_hibernating_state_balances(gpt_model, make_engine,
                                              tier_env):
    """The partition invariant with the new state: pages pinned under a
    hibernation hold count ``hibernating`` (strict audit at every
    transition), return to plain cache residency after demotion, and the
    aggregate hbm_bytes gains host_tier/disk_tier entries."""
    from penroz_tpu.serve import memledger, tierstore
    engine = make_engine("tiergpt", BLOCK, 0.0, None, capacity=2)
    prompt = [1, 2, 3, 4, 5, 6, 7]
    _submit(engine, prompt, 4, session_id="ledger").result()
    _wait_tier("ledger", "host")
    snap = engine.memory_snapshot()
    pool = snap["pool_pages"]
    # demoted: the hold is released, pages are evictable cache residents
    assert pool["hibernating"] == 0
    assert pool["prefix_evictable"] > 0
    engine._ledger.audit("test.after_demote")
    agg = memledger.memory_stats()
    assert agg["hbm_bytes"]["host_tier"] \
        == tierstore.TIERS.tier_bytes()["host_tier"] > 0
    assert agg["pool_pages"]["hibernating"] == 0
    # DELETE while a later hold is pending: hibernate again, then drop
    # before demotion — the worker releases the pin, books still balance
    cont = _submit(engine, prompt + [8], 3, session_id="ledger2")
    cont.result()
    assert tierstore.TIERS.drop("ledger2", "api")
    _wait_tier("ledger", "host")     # original still resident
    engine._ledger.audit("test.after_drop")


@pytest.mark.parametrize("site", ["tier.demote", "tier.promote"])
def test_tier_fault_sites_crash_recover_with_parity(gpt_model, make_engine,
                                                    tier_env, monkeypatch,
                                                    site):
    """Both injection sites fail the tick into standard crash recovery:
    the engine resets, strict audits stay green, and the SAME histories
    then hibernate/resume with parity."""
    from penroz_tpu.utils import faults
    prompt = [1, 2, 3, 4, 5, 6, 7]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [9]
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)
    monkeypatch.setenv("PENROZ_FAULT_INJECT", f"{site}:raise@1")
    faults.reset()
    engine = make_engine("tiergpt", BLOCK, 0.0, None, capacity=2)
    if site == "tier.demote":
        # the generation succeeds; the async demotion tick crashes
        assert _submit(engine, prompt, 4, session_id="chaos").result() == out
        deadline = time.monotonic() + 60
        while engine.stats()["crashes_total"] < 1:
            assert time.monotonic() < deadline, "demote fault never fired"
            time.sleep(0.02)
    else:
        # hibernate cleanly first, then the WAKE admission crashes: the
        # client gets the injected error, not a hang
        assert _submit(engine, prompt, 4, session_id="chaos").result() == out
        _wait_tier("chaos", "host")
        # churn enough distinct prefixes through the 8-page radix region
        # to LRU-evict the session's copy, so the wake must import
        for j in range(5):
            filler = [30 + j] * 8
            _submit(engine, filler, 2).result()
        with pytest.raises(Exception, match="injected fault"):
            _submit(engine, cont, 3).result()
        assert engine.stats()["crashes_total"] == 1
    # disarmed now (raise@1): the full flow works on the recovered engine
    assert _submit(engine, prompt, 4, session_id="after").result() == out
    _wait_tier("after", "host")
    assert _submit(engine, cont, 3).result() == base
    assert engine.stats()["breaker_open"] is False


# -- HTTP surface ------------------------------------------------------------

@pytest.fixture
def client(workdir):
    import asyncio
    from penroz_tpu.serve import app as app_mod
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    from aiohttp.test_utils import TestClient, TestServer
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield client, loop
    loop.run_until_complete(client.close())
    loop.close()


def _json(client_loop, method, path, **kw):
    client, loop = client_loop

    async def go():
        resp = await client.request(method, path, **kw)
        import json as _json_mod
        body = await resp.read()
        return resp.status, (_json_mod.loads(body) if body else None)

    return loop.run_until_complete(go())


def test_sessions_api_surface(client, gpt_model, tier_env):
    """session_id on /generate/ hibernates; GET /sessions/ shows the
    residency across tiers; DELETE /sessions/{id} is an idempotent evict;
    session_ids on /generate_batch/ validates per row."""
    tier_env.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    payload = {"model_id": "tiergpt", "input": [[1, 2, 3, 4, 5]],
               "block_size": BLOCK, "max_new_tokens": 4,
               "temperature": 0.0, "session_id": "api-conv"}
    status, body = _json(client, "POST", "/generate/", json=payload)
    assert status == 200 and len(body["tokens"]) == 9
    deadline = time.monotonic() + 60
    while True:
        status, listing = _json(client, "GET", "/sessions/")
        assert status == 200
        if listing["sessions_by_tier"]["host"] == 1:
            break
        assert time.monotonic() < deadline, listing
        time.sleep(0.02)
    (sess,) = listing["sessions"]
    assert sess["session_id"] == "api-conv" and sess["tier"] == "host"
    assert sess["pages"] * 4 == sess["tokens"]
    assert listing["tier_bytes"]["host_tier"] > 0
    # malformed id: schema-rejected before any engine work (422)
    status, _ = _json(client, "POST", "/generate/",
                      json=dict(payload, session_id="bad id!"))
    assert status == 422
    # batched path: one id per row, null = no session
    status, body = _json(client, "POST", "/generate_batch/", json={
        "model_id": "tiergpt", "inputs": [[1, 2, 3], [4, 5]],
        "block_size": BLOCK, "max_new_tokens": 3, "temperature": 0.0,
        "session_ids": ["api-b0", None]})
    assert status == 200 and len(body["sequences"]) == 2
    # wrong arity is a 400 naming the mismatch
    status, err = _json(client, "POST", "/generate_batch/", json={
        "model_id": "tiergpt", "inputs": [[1, 2, 3], [4, 5]],
        "block_size": BLOCK, "max_new_tokens": 3, "temperature": 0.0,
        "session_ids": ["only-one"]})
    assert status == 400
    # delete: evicts everywhere, idempotent on re-delete
    status, body = _json(client, "DELETE", "/sessions/api-conv")
    assert status == 200 and body["deleted"] is True
    status, body = _json(client, "DELETE", "/sessions/api-conv")
    assert status == 200 and body["deleted"] is False
    status, listing = _json(client, "GET", "/sessions/")
    assert "api-conv" not in {s["session_id"]
                              for s in listing["sessions"]}
