"""REAL multi-host training: two OS processes, cross-process collectives.

Round-1 recorded multi-host as "only mock-tested (unavoidable here)"
(VERDICT.md §coverage row 25).  It is avoidable: ``jax.distributed`` works
on the CPU backend across local processes, so these tests launch two
workers with the production env wiring (coordinator address + process ids,
two virtual CPU devices each → a 4-device global mesh) and drive the full
``train_model`` / ``evaluate_model`` stack — gradient psum across
processes, rank-strided loaders, ``all_reduce_mean``, and (FSDP case)
cross-host shard-file checkpointing all execute for real.

The subprocess env is scrubbed of the accelerator plugin (sitecustomize on
PYTHONPATH would capture JAX_PLATFORMS before the worker can force cpu —
same failure mode conftest.py guards against in-process).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# CI tier: real multi-process jax.distributed runs (slowest shard).
pytestmark = pytest.mark.multihost

_LAYERS = [
    {"summation": [
        {"embedding": {"num_embeddings": 64, "embedding_dim": 32},
         "normal": {"mean": 0.0, "std": 0.02}},
        {"position": {"num_embeddings": 16, "embedding_dim": 32},
         "normal": {"mean": 0.0, "std": 0.02}}]},
    {"residual": [
        {"sequential": [
            {"layernorm": {"normalized_shape": 32}},
            {"linear": {"in_features": 32, "out_features": 96}},
            {"attention": {"num_heads": 4, "dropout": 0.0}},
            {"linear": {"in_features": 32, "out_features": 32}}]},
        {"sequential": [
            {"layernorm": {"normalized_shape": 32}},
            {"linear": {"in_features": 32, "out_features": 64}},
            {"gelu": {}},
            {"linear": {"in_features": 64, "out_features": 32}}]}]},
    {"layernorm": {"normalized_shape": 32}},
    {"linear": {"in_features": 32, "out_features": 64, "bias": False}},
    {"softmaxlast": {"dim": -1}},
]
_OPT = {"adamw": {"lr": 1e-3, "betas": [0.9, 0.95], "eps": 1e-8}}


def _cache_dir() -> str:
    """The conftest's machine-fingerprinted compile cache (XLA:CPU AOT
    results are host-ISA-exact; sharing across machines only spams
    mismatch errors)."""
    import jax
    return jax.config.jax_compilation_cache_dir


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(port: int, proc_id: int, extra: dict,
                devices: int = 2) -> dict:
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_", "PALLAS_", "PENROZ_",
                                "TURBO_", "PAGED_"))}
    env.pop("PYTHONPATH", None)  # drop the accelerator-plugin site dir
    env.update({
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices}",
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
        "JAX_PROCESS_ID": str(proc_id),
        "JAX_COMPILATION_CACHE_DIR": _cache_dir(),
    })
    env.update(extra)
    return env


def _run_pair(tmp_path, model_id: str, extra_env: dict, epochs: int = 2,
              devices_per_proc: int = 2, layers=None):
    data_dir = tmp_path / "data"
    data_dir.mkdir(exist_ok=True)
    rng = np.random.default_rng(0)
    np.save(data_dir / "mh_000000",
            rng.integers(0, 64, 8000).astype(np.uint16))
    cfg = {"workdir": str(tmp_path), "model_id": model_id, "dataset": "mh",
           "layers": layers or _LAYERS, "optimizer": _OPT, "epochs": epochs,
           "batch_size": 8, "block_size": 16, "step_size": 8}
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "_multihost_worker.py"),
         json.dumps(cfg)],
        env=_worker_env(port, i, extra_env, devices=devices_per_proc),
        cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)]
    outs = []
    for p in procs:
        try:
            # 600s: these workers compile real multi-process programs on a
            # shared CPU that may concurrently run other suites/benches —
            # 420s flaked under load (r04) with both workers healthy.
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
    return outs


def _assert_reassembles(tmp_path, model_id: str):
    """A fresh single (non-distributed) process must reassemble the
    cross-host-sharded checkpoint into finite full arrays."""
    code = (
        "import os, numpy as np\n"
        f"os.chdir({str(tmp_path)!r})\n"
        "from penroz_tpu.utils import checkpoint\n"
        f"checkpoint.SHM_PATH = os.path.join({str(tmp_path)!r}, 'shm')\n"
        "from penroz_tpu.models.model import NeuralNetworkModel\n"
        f"m = NeuralNetworkModel.deserialize({model_id!r})\n"
        "assert m.status['code'] == 'Trained', m.status\n"
        "for k, v in m.params.items():\n"
        "    assert np.isfinite(np.asarray(v, np.float32)).all(), k\n"
        "print('reassembled', len(m.params))\n")
    env = _worker_env(_free_port(), 0, {})
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        env.pop(k)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         cwd=str(tmp_path), capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "reassembled" in out.stdout


def test_real_two_process_dp_training(tmp_path):
    """Two processes, 4-device global DP mesh: gradient sync across OS
    processes keeps the replicas bit-identical, and the eval cost
    all_reduce_mean agrees on both hosts."""
    _run_pair(tmp_path, "mhdp", {})
    d0 = np.load(tmp_path / "proc0.npz")
    d1 = np.load(tmp_path / "proc1.npz")
    # same eval cost on every host (the reference's ddp_all_reduce contract,
    # neural_net_model.py:352-354)
    assert float(d0["cost"]) == pytest.approx(float(d1["cost"]), abs=1e-6)
    # replicas did not diverge: cross-process grad psum really synced them
    keys = [k for k in d0.files if k != "cost"]
    assert keys, "workers dumped no params"
    for k in keys:
        np.testing.assert_array_equal(d0[k], d1[k])
    # per-rank log separation (reference ddp.py:87-114 analog): every
    # process mirrored its records into its own rank-tagged file
    for rank in (0, 1):
        path = tmp_path / "logs" / f"penroz_rank{rank}.log"
        assert path.exists(), f"missing per-rank log {path}"
        content = path.read_text()
        assert f"[rank{rank}/2]" in content
        assert f"Per-rank logging for process {rank}/2" in content
        # training records landed in the file, not just the banner
        assert "Epoch" in content or "Training" in content, content[-500:]


def test_real_two_process_fsdp_checkpoint(tmp_path):
    """FSDP across processes: params are cross-host sharded, every process
    writes its shard file, and a fresh single process reassembles the full
    checkpoint (the saves_shards-over-all-items path, for real)."""
    _run_pair(tmp_path, "mhfsdp", {"PENROZ_FSDP": "1"})
    shard_files = list(tmp_path.glob("models/*.shard*.ckpt"))
    assert len(shard_files) == 2, \
        f"expected one shard file per process, got {shard_files}"
    # a fresh single process must reassemble the cross-host-sharded state
    _assert_reassembles(tmp_path, "mhfsdp")


def test_real_tensor_parallel_across_hosts(tmp_path):
    """One device per process, PENROZ_MESH_MODEL=2: the model axis itself
    spans the two OS processes, so every TP all-gather/reduce-scatter and
    the per-host shard-file checkpointing run cross-process for real (the
    round-1 'pure DP only' multi-host restriction, exercised end-to-end)."""
    _run_pair(tmp_path, "mhtp", {"PENROZ_MESH_MODEL": "2"},
              devices_per_proc=1)
    # TP-sharded params cross hosts → per-process shard files
    shard_files = list(tmp_path.glob("models/*.shard*.ckpt"))
    assert len(shard_files) == 2
    # both hosts agree on the eval cost
    d0 = np.load(tmp_path / "proc0.npz")
    d1 = np.load(tmp_path / "proc1.npz")
    assert float(d0["cost"]) == pytest.approx(float(d1["cost"]), abs=1e-6)


_PIPE_BLOCK = {"residual": [
    {"sequential": [
        {"layernorm": {"normalized_shape": 32}},
        {"linear": {"in_features": 32, "out_features": 96}},
        {"attention": {"num_heads": 4, "dropout": 0.0}},
        {"linear": {"in_features": 32, "out_features": 32}}]}]}

_PIPE_LAYERS = [
    {"summation": [
        {"embedding": {"num_embeddings": 64, "embedding_dim": 32},
         "normal": {"mean": 0.0, "std": 0.02}},
        {"position": {"num_embeddings": 16, "embedding_dim": 32},
         "normal": {"mean": 0.0, "std": 0.02}}]},
    _PIPE_BLOCK, _PIPE_BLOCK,
    {"layernorm": {"normalized_shape": 32}},
    {"linear": {"in_features": 32, "out_features": 64, "bias": False}},
    {"softmaxlast": {"dim": -1}},
]


def _single_process_costs(tmp_path, model_id: str, epochs: int = 2):
    """Reference run: same data/config on one process, single device."""
    code = (
        "import os, json, numpy as np\n"
        f"os.chdir({str(tmp_path)!r})\n"
        "from penroz_tpu.utils import checkpoint\n"
        f"checkpoint.SHM_PATH = os.path.join({str(tmp_path)!r}, 'shm')\n"
        "os.makedirs(checkpoint.SHM_PATH, exist_ok=True)\n"
        "from penroz_tpu.models.dsl import Mapper\n"
        "from penroz_tpu.models.model import NeuralNetworkModel\n"
        f"layers = json.loads({json.dumps(json.dumps(_PIPE_LAYERS))})\n"
        f"opt = json.loads({json.dumps(json.dumps(_OPT))})\n"
        f"m = NeuralNetworkModel({model_id!r}, Mapper(layers, opt))\n"
        "m.to_device('cpu')\n"
        f"m.train_model('mh', shard=0, epochs={epochs}, batch_size=8, "
        "block_size=16, step_size=8)\n"
        "assert m.status['code'] == 'Trained', m.status\n"
        "print(json.dumps([p['cost'] for p in m.progress]))\n")
    env = _worker_env(_free_port(), 0, {"PENROZ_TRAIN_MESH": "0"},
                      devices=1)
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        env.pop(k)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         cwd=str(tmp_path), capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_real_pipeline_stages_across_hosts(tmp_path):
    """PENROZ_MESH_PIPE=2 over two OS processes (2 virtual devices each):
    the pipe axis is outermost, so stage 0 lives entirely on process 0 and
    stage 1 on process 1 — every GPipe ppermute handoff crosses the
    process boundary for real.  Per-epoch costs must match a single-device
    run on the identical data (the schedule is the same math), and a fresh
    single process must be able to load the resulting checkpoint."""
    _run_pair(tmp_path, "mhpipe", {"PENROZ_MESH_PIPE": "2"},
              layers=_PIPE_LAYERS)
    d0 = np.load(tmp_path / "proc0.npz")
    d1 = np.load(tmp_path / "proc1.npz")
    assert float(d0["cost"]) == pytest.approx(float(d1["cost"]), abs=1e-6)

    # training costs == single-device run on the same data (no DP across
    # hosts: both processes fed identical batches)
    ref_costs = _single_process_costs(tmp_path, "mhpipe_ref")
    code = (
        "import os, json\n"
        f"os.chdir({str(tmp_path)!r})\n"
        "from penroz_tpu.utils import checkpoint\n"
        f"checkpoint.SHM_PATH = os.path.join({str(tmp_path)!r}, 'shm')\n"
        "from penroz_tpu.models.model import NeuralNetworkModel\n"
        "m = NeuralNetworkModel.deserialize('mhpipe')\n"
        "assert m.status['code'] == 'Trained', m.status\n"
        "import numpy as np\n"
        "for k, v in m.params.items():\n"
        "    assert np.isfinite(np.asarray(v)).all(), k\n"
        "print(json.dumps([p['cost'] for p in m.progress]))\n")
    env = _worker_env(_free_port(), 0, {})
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        env.pop(k)
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         cwd=str(tmp_path), capture_output=True, text=True,
                         timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    pipe_costs = json.loads(out.stdout.strip().splitlines()[-1])
    assert len(pipe_costs) == len(ref_costs) and pipe_costs
    for a, b in zip(pipe_costs, ref_costs):
        assert a == pytest.approx(b, rel=2e-4), (pipe_costs, ref_costs)


def test_real_pipeline_with_fsdp_across_hosts(tmp_path):
    """PENROZ_MESH_PIPE=2 + PENROZ_FSDP=1 over two OS processes: stages
    span the processes AND the stacked param storage data-shards within
    each stage's host — the ZeRO×PP composition exercised with real
    cross-process collectives, shard-file checkpointing included."""
    _run_pair(tmp_path, "mhpipez",
              {"PENROZ_MESH_PIPE": "2", "PENROZ_FSDP": "1"},
              layers=_PIPE_LAYERS)
    d0 = np.load(tmp_path / "proc0.npz")
    d1 = np.load(tmp_path / "proc1.npz")
    assert float(d0["cost"]) == pytest.approx(float(d1["cost"]), abs=1e-6)
    assert np.isfinite(float(d0["cost"]))
    # the pipe-stacked, FSDP-sharded state really went through the
    # shard-file path (one file per process), not a whole-blob fallback
    shard_files = list(tmp_path.glob("models/*.shard*.ckpt"))
    assert len(shard_files) == 2, shard_files
    _assert_reassembles(tmp_path, "mhpipez")
