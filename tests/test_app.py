"""REST API tests (aiohttp TestClient — mirrors the reference's FastAPI
TestClient coverage in test_main.py: route behavior, lock 409s, gzip,
streaming, error mapping)."""

import asyncio
import gzip
import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from penroz_tpu.serve import app as app_mod

# CI tier: heavier compiles (see pyproject markers / ci.yml shards).
pytestmark = pytest.mark.runtime

TOY_LAYERS = [
    {"embedding": {"num_embeddings": 32, "embedding_dim": 8}},
    {"linear": {"in_features": 8, "out_features": 32}},
    {"softmaxlast": {"dim": -1}},
]
SGD = {"sgd": {"lr": 0.1}}


@pytest.fixture
def client(workdir, event_loop=None):
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield _SyncClient(client, loop)
    loop.run_until_complete(client.close())
    loop.close()


class _SyncClient:
    """Synchronous facade over the async TestClient."""

    def __init__(self, client, loop):
        self._client = client
        self._loop = loop

    def request(self, method, path, **kw):
        async def go():
            resp = await self._client.request(method, path, **kw)
            body = await resp.read()
            return resp, body
        return self._loop.run_until_complete(go())

    def json(self, method, path, **kw):
        resp, body = self.request(method, path, **kw)
        return resp.status, (json.loads(body) if body else None)


def _create_model(client, model_id="m1", layers=None, optimizer=None):
    status, body = client.json("POST", "/model/", json={
        "model_id": model_id,
        "layers": layers or TOY_LAYERS,
        "optimizer": optimizer or SGD,
    })
    assert status == 200, body
    return body


def _make_shards(workdir, dataset_id="ds", vocab=32):
    (workdir / "data").mkdir(exist_ok=True)
    rng = np.random.default_rng(0)
    np.save(workdir / "data" / f"{dataset_id}_000000",
            rng.integers(0, vocab, 4000).astype(np.uint16))


def test_create_model(client):
    body = _create_model(client)
    assert "created and saved successfully" in body["message"]


def test_root_redirects_to_dashboard(client):
    resp, body = client.request("GET", "/")
    assert resp.status == 200
    assert b"dashboard" in body


def test_output_route(client):
    _create_model(client)
    status, body = client.json("POST", "/output/", json={
        "model_id": "m1", "input": [[1, 2]], "target": [[2, 3]]})
    assert status == 200
    assert len(body["output"][0]) == 32
    assert body["cost"] > 0


def test_generate_route(client):
    _create_model(client)
    status, body = client.json("POST", "/generate/", json={
        "model_id": "m1", "input": [[1, 2]], "block_size": 8,
        "max_new_tokens": 3, "temperature": 0.0})
    assert status == 200
    assert len(body["tokens"]) == 5


def test_generate_batch_route(client):
    """/generate_batch/: ragged prompts, per-row greedy outputs equal the
    single-sequence route."""
    _create_model(client)
    status, body = client.json("POST", "/generate_batch/", json={
        "model_id": "m1", "inputs": [[1, 2, 3], [5]], "block_size": 8,
        "max_new_tokens": 3, "temperature": 0.0})
    assert status == 200
    assert len(body["sequences"]) == 2
    assert body["sequences"][0][:3] == [1, 2, 3]
    assert body["sequences"][1][:1] == [5]
    for row in body["sequences"]:
        _, single = client.json("POST", "/generate/", json={
            "model_id": "m1", "input": [row[:len(row) - 3]], "block_size": 8,
            "max_new_tokens": 3, "temperature": 0.0})
        assert single["tokens"] == row
    # oversized request → 400
    status, _ = client.json("POST", "/generate_batch/", json={
        "model_id": "m1", "inputs": [[1] * 7], "block_size": 8,
        "max_new_tokens": 3, "temperature": 0.0})
    assert status == 400


def test_generate_streaming(client):
    _create_model(client)
    resp, body = client.request("POST", "/generate/", json={
        "model_id": "m1", "input": [[1]], "block_size": 8,
        "max_new_tokens": 4, "stream": True})
    assert resp.status == 200
    assert resp.headers["Content-Type"].startswith("text/plain")
    lines = body.decode().strip().split("\n")
    assert len(lines) == 4
    assert all(line.isdigit() for line in lines)


def test_train_route_202_and_progress(client, workdir):
    _create_model(client)
    _make_shards(workdir)
    status, body = client.json("PUT", "/train/", json={
        "model_id": "m1", "device": "cpu", "dataset_id": "ds", "shard": 0,
        "epochs": 2, "batch_size": 2, "block_size": 8, "step_size": 1})
    assert status == 202
    assert "asynchronously" in body["message"]
    import time
    for _ in range(300):
        status, body = client.json("GET", "/progress/?model_id=m1")
        if body["status"]["code"] in ("Trained", "Error"):
            break
        time.sleep(0.2)
    assert body["status"]["code"] == "Trained", body["status"]
    assert len(body["progress"]) == 2
    assert body["average_cost"] is not None
    status, stats = client.json("GET", "/stats/?model_id=m1")
    assert status == 200
    assert len(stats["layers"]) >= 2


def test_generate_while_training(client, workdir):
    """Serving-under-training policy: a /generate/ arriving mid-/train/ is
    served from the latest checkpoint (it never shares the training
    thread's in-memory params) while the epoch loop owns the device; it
    must return 200 with valid tokens, and training must still complete.
    The latency cost of the device contention is measured on-chip by
    bench.py (ttft_under_train_ms_p50); see README "Serving while
    training"."""
    import time
    _create_model(client)
    _make_shards(workdir)
    status, _ = client.json("PUT", "/train/", json={
        "model_id": "m1", "device": "cpu", "dataset_id": "ds", "shard": 0,
        "epochs": 400, "batch_size": 2, "block_size": 8, "step_size": 1})
    assert status == 202
    served_during = 0
    code = None
    for _ in range(600):
        _, body = client.json("GET", "/progress/?model_id=m1")
        code = body["status"]["code"]
        if code == "Training":
            gs, gb = client.json("POST", "/generate/", json={
                "model_id": "m1", "input": [[1, 2]], "block_size": 8,
                "max_new_tokens": 2, "temperature": 0.0})
            assert gs == 200, gb
            assert len(gb["tokens"]) == 4
            served_during += 1
        if code in ("Trained", "Error"):
            break
        time.sleep(0.05)
    assert code == "Trained", code
    assert served_during > 0, "training finished before any mid-run generate"


def test_train_unknown_model_404(client):
    status, body = client.json("PUT", "/train/", json={
        "model_id": "nope", "device": "cpu", "dataset_id": "ds", "shard": 0,
        "epochs": 1, "batch_size": 2, "block_size": 8, "step_size": 1})
    assert status == 404


def test_train_conflict_409(client, workdir):
    _create_model(client)
    lock = app_mod.model_locks.setdefault("m1", asyncio.Lock())
    client._loop.run_until_complete(lock.acquire())
    try:
        status, body = client.json("PUT", "/train/", json={
            "model_id": "m1", "device": "cpu", "dataset_id": "ds", "shard": 0,
            "epochs": 1, "batch_size": 2, "block_size": 8, "step_size": 1})
        assert status == 409
        assert "already in progress" in body["detail"]
    finally:
        lock.release()


def test_dataset_download_409_and_list(client, workdir):
    lock = app_mod.dataset_locks.setdefault("dl", asyncio.Lock())
    client._loop.run_until_complete(lock.acquire())
    try:
        status, body = client.json("POST", "/dataset/", json={
            "dataset_id": "dl", "encoding": "byte", "path": "p",
            "name": "n", "split": "train", "shard_size": 100})
        assert status == 409
    finally:
        lock.release()
    _make_shards(workdir, "listme")
    status, body = client.json("GET", "/dataset/?dataset_id=listme")
    assert body["files"] == ["listme_000000.npy"]


def test_dataset_delete_204(client, workdir):
    _make_shards(workdir, "deadds")
    resp, _ = client.request("DELETE", "/dataset/?dataset_id=deadds")
    assert resp.status == 204
    status, body = client.json("GET", "/dataset/?dataset_id=deadds")
    assert body["files"] == []


def test_tokenize_and_decode(client):
    status, body = client.json("POST", "/tokenize/", json={
        "encoding": "byte", "text": "ab"})
    assert status == 200
    assert body["tokens"] == [97, 98, 256]
    status, body = client.json("POST", "/decode/", json={
        "encoding": "byte", "tokens": [97, 98]})
    assert body["text"] == "ab"


def test_evaluate_route(client, workdir):
    _create_model(client)
    _make_shards(workdir)
    status, body = client.json("POST", "/evaluate/", json={
        "model_id": "m1", "device": "cpu", "dataset_id": "ds", "shard": 0,
        "epochs": 1, "batch_size": 2, "block_size": 8, "step_size": 1})
    assert status == 200
    assert body["cost"] > 0


def test_gzip_request_body(client):
    payload = gzip.compress(json.dumps(
        {"encoding": "byte", "text": "zip"}).encode())
    resp, body = client.request(
        "POST", "/tokenize/", data=payload,
        headers={"Content-Type": "application/json",
                 "Content-Encoding": "gzip"})
    assert resp.status == 200
    assert json.loads(body)["tokens"] == [122, 105, 112, 256]


def test_error_mapping(client):
    # 404: unknown model
    status, body = client.json("GET", "/progress/?model_id=ghost")
    assert status == 404
    assert "Not found" in body["detail"]
    # 422: validation error
    status, body = client.json("POST", "/generate/", json={"model_id": "x"})
    assert status == 422
    # 422: missing query param
    status, body = client.json("GET", "/progress/")
    assert status == 422
    # 400: bad layer DSL (ValueError)
    status, body = client.json("POST", "/model/", json={
        "model_id": "bad", "layers": [{"nonsense": {}}], "optimizer": SGD})
    assert status == 400
    assert "Value error" in body["detail"]


def test_delete_model_204_then_404(client):
    _create_model(client, "gone")
    resp, _ = client.request("DELETE", "/model/?model_id=gone")
    assert resp.status == 204
    status, _ = client.json("GET", "/progress/?model_id=gone")
    assert status == 404


def test_model_locks_shared_between_train_and_import(client):
    """/import/ and /train/ share the per-model lock namespace."""
    lock = app_mod.model_locks.setdefault("shared", asyncio.Lock())
    client._loop.run_until_complete(lock.acquire())
    try:
        status, _ = client.json("POST", "/import/", json={
            "hf_repo_id": "openai-community/gpt2", "model_id": "shared"})
        assert status == 409
    finally:
        lock.release()


def test_ops_files_present_and_valid():
    """run scripts, log config, CI workflow (parity: reference test_run_sh)."""
    import json, os, stat
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for script in ("run.sh", "run-in-vm.sh"):
        path = os.path.join(root, script)
        assert os.path.exists(path)
        assert os.stat(path).st_mode & stat.S_IXUSR
        with open(path) as f:
            content = f.read()
        assert content.startswith("#!/bin/bash")
        assert "penroz_tpu.serve.app" in content
    with open(os.path.join(root, "log_config.json")) as f:
        cfg = json.load(f)
    assert cfg["version"] == 1
    assert "aiohttp.access" in cfg["loggers"]
    import logging.config
    logging.config.dictConfig(cfg)  # must be a valid dictConfig
    assert os.path.exists(os.path.join(root, ".github", "workflows",
                                       "ci.yml"))


def test_profile_start_stop_roundtrip(client, tmp_path):
    """POST /profile/ start → trace capture → stop writes trace files."""
    log_dir = str(tmp_path / "prof")
    status, _ = client.json("POST", "/profile/",
                            json={"action": "start", "log_dir": log_dir})
    assert status == 200
    # a second start while capturing → 409
    status, _ = client.json("POST", "/profile/",
                            json={"action": "start", "log_dir": log_dir})
    assert status == 409
    import jax.numpy as jnp
    (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
    status, _ = client.json("POST", "/profile/", json={"action": "stop"})
    assert status == 200
    import os
    found = [f for _, _, fs in os.walk(log_dir) for f in fs]
    assert found, "trace capture produced no files"
    # stop when idle → 409
    status, _ = client.json("POST", "/profile/", json={"action": "stop"})
    assert status == 409


def test_profile_unknown_action(client):
    status, _ = client.json("POST", "/profile/", json={"action": "bogus"})
    assert status == 400


def test_configure_logging_all_paths(monkeypatch, tmp_path, capsys):
    """Regression: the basicConfig fallback crashed with UnboundLocalError
    when PENROZ_LOG_CONFIG was unset (branch-local `import logging.config`
    shadowed the module-level `logging` name)."""
    monkeypatch.delenv("PENROZ_LOG_CONFIG", raising=False)
    app_mod._configure_logging()  # must not raise
    monkeypatch.setenv("PENROZ_LOG_CONFIG", str(tmp_path / "missing.json"))
    app_mod._configure_logging()
    assert "does not exist" in capsys.readouterr().err
    config = tmp_path / "log.json"
    config.write_text(json.dumps({
        "version": 1, "disable_existing_loggers": False,
        "handlers": {"default": {"class": "logging.StreamHandler"}},
        "root": {"handlers": ["default"]}}))
    monkeypatch.setenv("PENROZ_LOG_CONFIG", str(config))
    app_mod._configure_logging()


def test_openapi_spec(client):
    """OpenAPI parity with the reference's FastAPI docs surface: the spec
    covers every route and /model/ carries the GPT-2-124M example
    (reference: main.py:53-93)."""
    status, spec = client.json("GET", "/openapi.json")
    assert status == 200
    assert spec["openapi"].startswith("3.")
    for path in ["/model/", "/import/", "/dataset/", "/tokenize/",
                 "/output/", "/evaluate/", "/generate/", "/decode/",
                 "/train/", "/progress/", "/stats/", "/serving_stats/",
                 "/profile/", "/profiler/trace/", "/metrics", "/trace/",
                 "/trace/{request_id}", "/dashboard", "/healthz",
                 "/readyz"]:
        assert path in spec["paths"], path
    assert set(spec["paths"]["/dataset/"]) == {"get", "post", "delete"}
    assert "CreateModelRequest" in spec["components"]["schemas"]
    example = (spec["paths"]["/model/"]["post"]["requestBody"]["content"]
               ["application/json"]["example"])
    assert example["model_id"] == "gpt2-124M"
    embed = example["layers"][0]["summation"][0]["embedding"]
    assert embed == {"num_embeddings": 50257, "embedding_dim": 768}
    blocks = [l for l in example["layers"] if "residual" in l]
    assert len(blocks) == 12
    assert "adamw" in example["optimizer"]


def test_docs_page(client):
    resp, body = client.request("GET", "/docs")
    assert resp.status == 200
    assert "text/html" in resp.headers["Content-Type"]
    assert b"openapi.json" in body


def test_train_bad_device_400s_before_202(client, toy_shards_appdir=None):
    """A device typo must 400 synchronously, not 202 then silently no-op in
    the background task."""
    _create_model(client, "devcheck")
    status, body = client.json("PUT", "/train/", json={
        "model_id": "devcheck", "dataset_id": "nope", "shard": 0,
        "epochs": 1, "batch_size": 1, "block_size": 4, "step_size": 1,
        "device": "tpuu"})
    assert status == 400


def test_orphaned_training_swept_at_startup(workdir):
    """A checkpoint stuck in 'Training' (server killed mid-run) must read
    Error after a restart — training runs in the server process, so no run
    can survive one.  Other statuses pass through untouched."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    from penroz_tpu.utils import checkpoint

    for mid, code in (("orph", "Training"), ("done", "Trained")):
        m = NeuralNetworkModel(mid, Mapper(TOY_LAYERS, SGD))
        m.status = {"code": code, "message": None}
        m.serialize(sync_flush=True)

    app_mod._sweep_orphaned_training()

    swept = checkpoint.peek_tree("orph")["status"]
    assert swept["code"] == "Error"
    assert "restart" in swept["message"]
    assert checkpoint.peek_tree("done")["status"]["code"] == "Trained"
    # weights survive the metadata rewrite
    restored = NeuralNetworkModel.deserialize("orph")
    assert restored.params


def test_sweep_runs_at_create_app_and_tolerates_corrupt_checkpoints(workdir):
    """create_app() itself runs the orphan sweep synchronously (a client
    retrying /train/ right after restart must not race it), a healthy
    checkpoint is left alone, and an unreadable/corrupt checkpoint file in
    the models dir must not block startup."""
    import os
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    from penroz_tpu.utils import checkpoint

    for mid, code in (("stale", "Training"), ("healthy", "Trained")):
        m = NeuralNetworkModel(mid, Mapper(TOY_LAYERS, SGD))
        m.status = {"code": code, "message": None}
        m.serialize(sync_flush=True)
    # garbage that list_model_ids will pick up but peek_tree cannot parse
    os.makedirs("models", exist_ok=True)
    with open("models/model_corrupt.ckpt", "wb") as f:
        f.write(b"\x00garbage, not a container")

    app_mod.create_app()  # must not raise despite the corrupt file

    assert checkpoint.peek_tree("stale")["status"]["code"] == "Error"
    assert "restart" in checkpoint.peek_tree("stale")["status"]["message"]
    assert checkpoint.peek_tree("healthy")["status"]["code"] == "Trained"


@pytest.fixture
def fake_datasets(monkeypatch):
    """A stub HuggingFace `datasets` module: download exercises the REAL
    tokenize/shard pipeline, only the network fetch is faked."""
    import sys
    import types
    mod = types.SimpleNamespace(
        load_dataset=lambda path, name, split: {"text": ["hello world"] * 4})
    monkeypatch.setitem(sys.modules, "datasets", mod)
    return mod


def _poll_download(client, dataset_id, timeout_s=30):
    import time
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        status, body = client.json("GET", f"/dataset/?dataset_id={dataset_id}")
        assert status == 200
        dl = body.get("download")
        if dl and dl["state"] in ("complete", "failed"):
            return body
        time.sleep(0.05)
    raise AssertionError(f"download for {dataset_id} never settled")


def test_download_retries_through_injected_fault(client, workdir,
                                                 fake_datasets, monkeypatch):
    """A transient download failure (injected at the data.download site) is
    retried with backoff and succeeds on attempt 2 — shards exist and the
    dataset status reports the attempt count."""
    from penroz_tpu.utils import faults
    monkeypatch.setenv(faults.ENV, "data.download:raise@1")
    monkeypatch.setenv("PENROZ_DOWNLOAD_RETRIES", "3")
    monkeypatch.setenv("PENROZ_DOWNLOAD_BACKOFF_S", "0.01")
    faults.reset()
    status, _ = client.json("POST", "/dataset/", json={
        "dataset_id": "retryds", "encoding": "byte", "path": "p",
        "name": None, "split": "train", "shard_size": 64})
    assert status == 202
    body = _poll_download(client, "retryds")
    assert body["download"]["state"] == "complete"
    assert body["download"]["attempts"] == 2
    assert body["download"]["error"] is None
    assert body["files"], body
    faults.reset()


def test_download_terminal_failure_surfaced_to_clients(client, workdir,
                                                       fake_datasets,
                                                       monkeypatch):
    """Exhausted retries surface as state=failed with the error text in the
    dataset listing — clients see the terminal failure instead of a
    silently-logged fire-and-forget task."""
    from penroz_tpu.utils import faults
    monkeypatch.setenv(faults.ENV, "data.download:raise@1+")
    monkeypatch.setenv("PENROZ_DOWNLOAD_RETRIES", "2")
    monkeypatch.setenv("PENROZ_DOWNLOAD_BACKOFF_S", "0.01")
    faults.reset()
    status, _ = client.json("POST", "/dataset/", json={
        "dataset_id": "deadds2", "encoding": "byte", "path": "p",
        "name": None, "split": "train", "shard_size": 64})
    assert status == 202
    body = _poll_download(client, "deadds2")
    assert body["download"]["state"] == "failed"
    assert body["download"]["attempts"] == 2
    assert "InjectedFault" in body["download"]["error"]
    assert body["files"] == []
    faults.reset()


def test_stats_exposes_moe_router_fractions(client, workdir):
    """A trained MoE model's /stats/ carries per-expert routing fractions
    (additive key; expert collapse must be observable from the API)."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel

    d, vocab = 8, 32
    layers = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d}},
        {"moe": {"in_features": d, "intermediate_size": 2 * d,
                 "num_experts": 4, "top_k": 2}},
        {"linear": {"in_features": d, "out_features": vocab}},
        {"softmaxlast": {"dim": -1}}]
    import os as _os
    _os.makedirs("data", exist_ok=True)
    np.save("data/moestats_000000",
            np.random.randint(0, vocab, 4096).astype(np.uint16))
    model = NeuralNetworkModel("moest", Mapper(layers, SGD))
    model.train_model("moestats", shard=0, epochs=1, batch_size=2,
                      block_size=8, step_size=1)

    status, body = client.json("GET", "/stats/?model_id=moest")
    assert status == 200
    routing = body["moe_router_fractions"]
    (fractions,) = routing.values()
    assert len(fractions) == 4
    assert abs(sum(fractions) - 1.0) < 1e-5



def test_train_pipe_over_http(client, workdir, monkeypatch):
    """API-driven GPipe training: PUT /train/ with PENROZ_MESH_PIPE=2
    reaches Trained and the checkpoint serves /generate/ afterwards."""
    import time
    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    d, heads, vocab, block = 32, 4, 64, 16
    layers = ([{"summation": [
                  {"embedding": {"num_embeddings": vocab,
                                 "embedding_dim": d},
                   "normal": {"mean": 0.0, "std": 0.02}},
                  {"position": {"num_embeddings": block,
                                "embedding_dim": d},
                   "normal": {"mean": 0.0, "std": 0.02}}]}]
              + [{"residual": [
                  {"sequential": [
                      {"layernorm": {"normalized_shape": d}},
                      {"linear": {"in_features": d, "out_features": 3 * d},
                       "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                      {"attention": {"num_heads": heads, "dropout": 0.0}},
                      {"linear": {"in_features": d, "out_features": d}}]}]}
                 for _ in range(2)]
              + [{"layernorm": {"normalized_shape": d}},
                 {"linear": {"in_features": d, "out_features": vocab,
                             "bias": False}},
                 {"softmax": {"dim": -1}}])
    status, _ = client.json("POST", "/model/", json={
        "model_id": "ppapi", "layers": layers,
        "optimizer": {"sgd": {"lr": 0.1}}})
    assert status == 200
    data_dir = workdir / "data"
    data_dir.mkdir(exist_ok=True)
    rng = np.random.default_rng(0)
    np.save(data_dir / "ppds_000000",
            rng.integers(0, vocab, 4000).astype(np.uint16))
    status, body = client.json("PUT", "/train/", json={
        "model_id": "ppapi", "device": "cpu", "dataset_id": "ppds",
        "shard": 0, "epochs": 2, "batch_size": 8, "block_size": 16,
        "step_size": 8})
    assert status == 202
    for _ in range(600):
        status, body = client.json("GET", "/progress/?model_id=ppapi")
        if body["status"]["code"] in ("Trained", "Error"):
            break
        time.sleep(0.2)
    assert body["status"]["code"] == "Trained", body["status"]
    status, gen = client.json("POST", "/generate/", json={
        "model_id": "ppapi", "input": [1, 2, 3], "block_size": 16,
        "max_new_tokens": 4, "temperature": 0.0})
    assert status == 200 and len(gen["tokens"]) == 7
