"""Serving tests for the constant-memory sequence backends (ops/ssm.py).

Hybrid (attention + ssm blocks) and pure-SSM models ride the SAME unified
continuous-batching scheduler as attention-only models — same admission,
slot recycling, superstep dispatch, spec-decode verify/rollback, crash
recovery, and disagg hand-off.  The parity contract is unchanged: every
greedy sequence the scheduler returns must be token-identical to the same
request run alone through the legacy single-sequence path.  On top of
that, the defining property is asserted here: recurrent-state bytes do
NOT grow with generated length.
"""

import queue
import time

import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

# CI tier: heavier compiles (serving stack), same tier as test_app.
pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}
REP_PROMPT = [1, 2, 3, 1, 2, 3, 1, 2]


@pytest.fixture(autouse=True)
def _ssm_registry(workdir):
    """Fresh engine registry + fault/QoS/ledger counters per test."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import decode_scheduler, memledger, qos
    from penroz_tpu.utils import faults

    def _zero():
        faults.reset()
        qos.reset()
        KV.reset_unpin_underflow_count()
        memledger.reset()

    _zero()
    yield
    decode_scheduler.reset()
    _zero()


@pytest.fixture
def hybrid_model(workdir, toy_hybrid_layers):
    """Serialized toy hybrid: block 0 gated-SSM, block 1 attention."""
    model = NeuralNetworkModel("schedhyb", Mapper(toy_hybrid_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def pure_ssm_model(workdir, toy_ssm_layers):
    """Serialized pure-SSM toy: every block recurrent, no KV rows at all."""
    model = NeuralNetworkModel("schedpure", Mapper(toy_ssm_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def make_engine():
    from penroz_tpu.serve import decode_scheduler
    engines = []

    def build(*args, **kwargs):
        engine = decode_scheduler.DecodeEngine(*args, **kwargs)
        engines.append(engine)
        return engine

    yield build
    for engine in engines:
        engine.shutdown()


class _Collector:
    def __init__(self, prompt):
        self.q = queue.Queue()
        self.tokens = list(prompt)
        self.received = 0

    def on_event(self, kind, value):
        self.q.put((kind, value))

    def result(self, timeout=180):
        deadline = time.monotonic() + timeout
        while True:
            kind, value = self.q.get(
                timeout=max(deadline - time.monotonic(), 0.1))
            if kind == "token":
                self.tokens.append(value)
                self.received += 1
            elif kind == "done":
                return self.tokens
            else:
                raise value


def _submit(target, prompt, max_new):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt)
    target.submit(decode_scheduler.Request(prompt, max_new, None,
                                           collector.on_event))
    return collector


# -- parity through the unified scheduler -----------------------------------

def test_hybrid_concurrent_parity_and_state_bytes(hybrid_model, make_engine,
                                                  monkeypatch):
    """Two overlapping greedy requests on a hybrid model match the legacy
    path exactly, the engine reports recurrent-state bytes, and those
    bytes are IDENTICAL after a 2-token and a 10-token generation — the
    O(1) claim at the stats surface."""
    monkeypatch.setenv("PENROZ_MEMLEDGER_STRICT", "1")
    p1, p2 = [1, 2, 3], [5]
    base1 = hybrid_model.generate_tokens([p1], BLOCK, 10, temperature=0.0)
    base2 = hybrid_model.generate_tokens([p2], BLOCK, 2, temperature=0.0)
    engine = make_engine("schedhyb", BLOCK, 0.0, None, capacity=2)
    c1 = _submit(engine, p1, 10)
    c2 = _submit(engine, p2, 2)
    assert c2.result() == base2
    bytes_short = engine.stats()["ssm_state_bytes"]
    assert c1.result() == base1
    stats = engine.stats()
    assert stats["ssm_state_bytes"] == bytes_short > 0
    assert stats["ssm_rows"] == 0          # both rows retired
    # the ledger attributes the same bytes to the ssm_state component
    assert engine._ledger.snapshot()["hbm_bytes"]["ssm_state"] == bytes_short


def test_pure_ssm_slot_recycling_parity(pure_ssm_model, make_engine):
    """Capacity-2 pure-SSM engine serves 4 requests: recycled rows must
    re-zero their recurrent state (the shared decode step advances EVERY
    batch row, so a stale state would corrupt the newcomer — there is no
    mask protecting SSM rows the way KV tails are mask-protected)."""
    prompts = [[1, 2, 3], [5], [7, 8], [9, 10, 11, 12]]
    bases = [pure_ssm_model.generate_tokens([p], BLOCK, 5, temperature=0.0)
             for p in prompts]
    engine = make_engine("schedpure", BLOCK, 0.0, None, capacity=2)
    collectors = [_submit(engine, p, 5) for p in prompts]
    for collector, base in zip(collectors, bases):
        assert collector.result() == base
    stats = engine.stats()
    assert stats["completed"] == 4
    assert stats["ssm_state_bytes"] > 0


# superstep-1 arms are the slow half of the matrix (per-token dispatch);
# one stays in tier-1 as the fast sibling, the rest ride the slow lane
# (tier1_budget.py precedent — coverage kept, gate wall contained)
@pytest.mark.parametrize("paged_prefix,int8,superstep", [
    pytest.param(paged, int8, ss,
                 marks=([pytest.mark.slow]
                        if ss == "1" and (paged, int8) != (0, 0) else []))
    for paged in (0, 1) for int8 in (0, 1) for ss in ("1", "8")])
def test_hybrid_spec_parity_matrix(hybrid_model, make_engine, monkeypatch,
                                   paged_prefix, int8, superstep):
    """THE acceptance matrix for hybrid archs: greedy outputs with
    PENROZ_SPEC_DECODE=1 are token-identical to the legacy path across
    paged(+prefix-cache request) × int8 KV × superstep {1, 8} — with the
    verify/rollback path provably engaged (oracle drafts, full
    acceptance).  When a prefix cache is requested it is refused for SSM
    archs (recurrent state cannot be rebuilt from shared pages)."""
    from penroz_tpu.serve import decode_scheduler, spec_decode
    if paged_prefix:
        monkeypatch.setenv("PAGED_KV_CACHE", "1")
        monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    if int8:
        monkeypatch.setenv("TURBO_QUANT_KV_CACHE", "1")
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, superstep)
    monkeypatch.setenv("PENROZ_SPEC_DECODE", "1")
    base = hybrid_model.generate_tokens([REP_PROMPT], BLOCK, 6,
                                        temperature=0.0)
    def oracle(history, k, n):
        if len(history) < len(base) and history == base[:len(history)]:
            return [int(t) for t in base[len(history):len(history) + k]]
        return []

    monkeypatch.setattr(spec_decode, "propose", oracle)
    engine = make_engine("schedhyb", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, REP_PROMPT, 6).result() == base
    assert _submit(engine, REP_PROMPT, 6).result() == base
    stats = engine.stats()
    assert stats["spec_verify_steps"] > 0
    assert stats["spec_accept_rate"] == 1.0
    assert stats["ssm_state_bytes"] > 0
    # prefix cache never engages for SSM archs
    assert stats["prefix_cache"] is None


def test_hybrid_adversarial_drafter_exact_rollback(hybrid_model,
                                                   make_engine, monkeypatch):
    """Satellite: spec-decode rollback symmetry.  An always-wrong drafter
    forces a checkpoint-ring rewind on EVERY verify step; the stream must
    still be token-identical (KV truncates, SSM restores — both exact)."""
    from penroz_tpu.serve import spec_decode
    monkeypatch.setenv("PENROZ_SPEC_DECODE", "1")
    monkeypatch.setenv("PENROZ_SPEC_NGRAM", "1")
    base = hybrid_model.generate_tokens([REP_PROMPT], BLOCK, 6,
                                        temperature=0.0)

    def wrong(history, k, n):
        nxt = base[len(history)] if len(history) < len(base) else 0
        return [(int(nxt) + 1) % 64] * min(k, 2)   # first token always wrong

    monkeypatch.setattr(spec_decode, "propose", wrong)
    engine = make_engine("schedhyb", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, REP_PROMPT, 6).result() == base
    stats = engine.stats()
    assert stats["spec_drafted_tokens"] > 0
    assert stats["spec_accepted_tokens"] == 0


# -- feature gating ----------------------------------------------------------

def test_prefix_cache_refused_for_ssm_arch(hybrid_model, make_engine,
                                           monkeypatch):
    """PENROZ_PREFIX_CACHE=1 on an SSM arch logs the refusal and leaves
    the radix cache off — shared prefix pages cannot reconstitute a
    recurrent state, so hibernate/preempt/promote stay disabled too.

    Asserted via a logger-method spy, not caplog — other suite tests
    reconfigure logging handlers, which silently empties caplog (same
    workaround as test_attention's softcap-warning test)."""
    from penroz_tpu.serve import decode_scheduler
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    warnings = []
    monkeypatch.setattr(
        decode_scheduler.log, "warning",
        lambda msg, *args, **kw: warnings.append(msg % tuple(args)
                                                 if args else msg))
    engine = make_engine("schedhyb", BLOCK, 0.0, None, capacity=2)
    assert any("SSM" in m for m in warnings), warnings
    assert engine._prefix_cache is None
    assert engine._extra_pages == 0
    base = hybrid_model.generate_tokens([REP_PROMPT], BLOCK, 4,
                                        temperature=0.0)
    assert _submit(engine, REP_PROMPT, 4).result() == base
    assert engine.stats()["prefix_cache"] is None


def test_pipeline_stages_fall_back_for_ssm_arch(hybrid_model, make_engine,
                                                monkeypatch):
    """PENROZ_SERVE_PIPE_STAGES on an SSM arch falls back to unpiped
    serving (stage KV views slice attention pools only) — requests still
    complete with exact parity."""
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_RAGGED_ATTENTION", "1")
    monkeypatch.setenv("PENROZ_SERVE_PIPE_STAGES", "2")
    base = hybrid_model.generate_tokens([[1, 2, 3]], BLOCK, 5,
                                        temperature=0.0)
    engine = make_engine("schedhyb", BLOCK, 0.0, None, capacity=2)
    assert engine._pipe is None
    assert _submit(engine, [1, 2, 3], 5).result() == base


# -- fault injection ---------------------------------------------------------

def test_ssm_scan_crash_recovers_with_parity(hybrid_model, make_engine,
                                             monkeypatch):
    """An injected ssm.scan crash mid-dispatch fails in-flight requests
    cleanly, drops every recurrent state with the engine reset, and the
    next request is greedy-identical — under the strict memledger audit
    (no leaked ssm_state bytes)."""
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_MEMLEDGER_STRICT", "1")
    prompt = [1, 2, 3]
    base = hybrid_model.generate_tokens([prompt], BLOCK, 6, temperature=0.0)
    monkeypatch.setenv(faults.ENV, "ssm.scan:raise@1")
    engine = make_engine("schedhyb", BLOCK, 0.0, None, capacity=2)
    with pytest.raises(faults.InjectedFault):
        _submit(engine, prompt, 6).result()
    monkeypatch.delenv(faults.ENV)
    faults.reset()
    assert _submit(engine, prompt, 6).result() == base
    stats = engine.stats()
    assert stats["crashes_total"] == 1
    assert stats["engine_resets"] == 1
    assert stats["ssm_state_bytes"] > 0


def test_pure_ssm_scan_crash_recovers(pure_ssm_model, make_engine,
                                      monkeypatch):
    """Same recovery contract on a pure-SSM arch (no KV pool at all)."""
    from penroz_tpu.utils import faults
    prompt = [7, 8, 9]
    base = pure_ssm_model.generate_tokens([prompt], BLOCK, 5,
                                          temperature=0.0)
    monkeypatch.setenv(faults.ENV, "ssm.scan:raise@1")
    engine = make_engine("schedpure", BLOCK, 0.0, None, capacity=2)
    with pytest.raises(faults.InjectedFault):
        _submit(engine, prompt, 5).result()
    monkeypatch.delenv(faults.ENV)
    faults.reset()
    assert _submit(engine, prompt, 5).result() == base
    assert engine.stats()["engine_resets"] == 1


# -- disaggregated hand-off --------------------------------------------------

def _ssm_disagg_env(monkeypatch):
    from penroz_tpu.serve import router as router_mod
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_MEMLEDGER_STRICT", "1")
    monkeypatch.setenv(router_mod.DISAGG_ENV, "1")
    monkeypatch.setenv(router_mod.DISAGG_REPLICAS_ENV, "1")


def _get_router(monkeypatch, model_id, n=2):
    from penroz_tpu.serve import decode_scheduler, router
    monkeypatch.setenv(decode_scheduler.REPLICAS_ENV, str(n))
    engine = decode_scheduler.get_engine(model_id, BLOCK, 0.0, None)
    assert isinstance(engine, router.EngineRouter)
    return engine


def test_hybrid_disagg_handoff_carries_recurrent_state(hybrid_model,
                                                       monkeypatch):
    """The O(1) hand-off: a hybrid request prefilled on the prefill
    replica decodes on the decode replica with exact greedy parity — the
    export blob carried the constant-size recurrent planes next to the
    token-extent KV pages (a dropped state would desync every SSM block's
    logits immediately)."""
    from penroz_tpu.serve import decode_scheduler
    _ssm_disagg_env(monkeypatch)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    base = hybrid_model.generate_tokens([prompt], BLOCK, 5, temperature=0.0)
    router = _get_router(monkeypatch, "schedhyb", n=2)
    try:
        assert [e.role for e in router.replicas] == ["prefill", "decode"]
        assert _submit(router, prompt, 5).result() == base
        per = [e.stats() for e in router.replicas]
        assert sum(p["disagg_exports"] for p in per) == 1
        assert sum(p["disagg_imports"] for p in per) == 1
        assert sum(p["disagg_handoff_failures"] for p in per) == 0
    finally:
        decode_scheduler.reset()


def test_hybrid_ssm_handoff_fault_falls_back_with_parity(hybrid_model,
                                                         monkeypatch):
    """An ssm.handoff crash mid-export (the new fault site fires only for
    SSM archs) degrades exactly like disagg.handoff: monolithic prefill
    on the decode replica, greedy-identical output, failure counted.
    Transport pinned to the host codec — the d2d path re-stages through
    it on failure, which would mask the fallback being asserted."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    _ssm_disagg_env(monkeypatch)
    monkeypatch.setenv(decode_scheduler.DISAGG_TRANSPORT_ENV, "host")
    monkeypatch.setenv(faults.ENV, "ssm.handoff:raise@1")
    prompt = [1, 2, 3, 4, 5, 6, 7]
    base = hybrid_model.generate_tokens([prompt], BLOCK, 5, temperature=0.0)
    router = _get_router(monkeypatch, "schedhyb", n=2)
    try:
        assert _submit(router, prompt, 5).result() == base
        per = [e.stats() for e in router.replicas]
        assert sum(p["disagg_handoff_failures"] for p in per) == 1
        assert sum(p["disagg_imports"] for p in per) == 0
        assert per[1]["completed"] == 1
    finally:
        decode_scheduler.reset()


# -- /memory/ polling: the O(1) acceptance criterion -------------------------

@pytest.fixture
def client(workdir):
    import asyncio
    from penroz_tpu.serve import app as app_mod
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    from aiohttp.test_utils import TestClient, TestServer
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield client, loop
    loop.run_until_complete(client.close())
    loop.close()


def _get_json(client_loop, path):
    import json
    client, loop = client_loop

    async def go():
        resp = await client.request("GET", path)
        body = await resp.read()
        return resp.status, json.loads(body)

    return loop.run_until_complete(go())


def test_memory_endpoint_ssm_state_constant_while_length_grows(
        hybrid_model, client, monkeypatch):
    """THE acceptance poll: GET /memory/ reports an ssm_state HBM
    component that is byte-identical at two different generated lengths
    of a live row — recurrent state does not grow with tokens, observed
    end to end through the public memory ledger (not just stats())."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@120")  # slow decode
    engine = decode_scheduler.get_engine("schedhyb", BLOCK, 0.0, None)
    collector = _submit(engine, [1, 2, 3], 10)

    def row_len():
        with engine._cond:
            return max((int(n) for n in engine._lengths), default=0)

    def poll_ssm_state():
        status, body = _get_json(client, "/memory/")
        assert status == 200
        entry = next(e for e in body["engines"]
                     if e["model_id"] == "schedhyb")
        return entry["hbm_bytes"]["ssm_state"], body["hbm_bytes"]["ssm_state"]

    # sample once early and once later in the decode; require the row to
    # have provably advanced between the samples
    deadline = time.monotonic() + 120
    while collector.received < 1:
        assert time.monotonic() < deadline, "decode never started"
        try:
            kind, value = collector.q.get(timeout=1.0)
        except queue.Empty:
            continue
        assert kind == "token", kind
        collector.tokens.append(value)
        collector.received += 1
    len1 = row_len()
    first, first_agg = poll_ssm_state()
    assert first > 0 and first_agg == first
    while collector.received < 6:
        assert time.monotonic() < deadline, "decode stalled"
        try:
            kind, value = collector.q.get(timeout=1.0)
        except queue.Empty:
            continue
        assert kind == "token", kind
        collector.tokens.append(value)
        collector.received += 1
    len2 = row_len()
    second, second_agg = poll_ssm_state()
    assert len2 > len1                       # the sequence provably grew
    assert second == first                   # ...the recurrent state did not
    assert second_agg == first_agg
    faults.reset()
    monkeypatch.delenv(faults.ENV)
    collector.result()
    decode_scheduler.reset()
