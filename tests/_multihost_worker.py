"""Worker body for the REAL two-process multi-host tests.

Launched as a subprocess by ``test_multihost_real.py`` with a scrubbed
environment (no accelerator plugin on PYTHONPATH, ``JAX_PLATFORMS=cpu``,
two virtual CPU devices per process) and the standard multi-host env knobs
(``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``)
— the same wiring a TPU pod uses, so ``dist.initialize()`` takes the
production path and every collective (gradient psum over the global mesh,
``all_reduce_mean`` of the eval cost, shard-file checkpointing) runs for
real across OS processes rather than being mocked.
"""

import json
import os
import sys


def main():
    cfg = json.loads(sys.argv[1])
    os.chdir(cfg["workdir"])
    from penroz_tpu.utils import checkpoint
    checkpoint.SHM_PATH = os.path.join(cfg["workdir"], "shm")
    os.makedirs(checkpoint.SHM_PATH, exist_ok=True)

    from penroz_tpu.parallel import dist
    assert dist.initialize(), "JAX_* multi-host env vars not picked up"

    import numpy as np
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel

    model = NeuralNetworkModel(cfg["model_id"],
                               Mapper(cfg["layers"], cfg["optimizer"]))
    model.to_device("cpu")
    model.train_model(cfg["dataset"], shard=0, epochs=cfg["epochs"],
                      batch_size=cfg["batch_size"],
                      block_size=cfg["block_size"],
                      step_size=cfg["step_size"])
    rank = dist.process_index()
    cost = model.evaluate_model(cfg["dataset"], None, 0, 1,
                                cfg["batch_size"], cfg["block_size"],
                                cfg["step_size"])
    dump = {"cost": np.float32(cost)}
    for k, v in model.params.items():
        if (getattr(v, "is_fully_addressable", True)
                or getattr(v, "is_fully_replicated", False)):
            dump[k.replace("/", "_")] = np.asarray(v, np.float32)
    np.savez(os.path.join(cfg["workdir"], f"proc{rank}.npz"), **dump)
    print(f"worker {rank} done status={model.status['code']}", flush=True)
    assert model.status["code"] == "Trained", model.status


if __name__ == "__main__":
    main()
