"""Checkpoint container codec: non-executable load (no pickle).

The reference persists models as ``torch.save`` pickles
(neural_net_model.py:116) whose load can execute arbitrary code; the
penroz container is JSON header + raw array bytes (checkpoint.py module
docstring), so these tests pin round-trip fidelity — including the bits
pickle got for free: int dict keys, bf16 dtypes, nested structure — and
that pickle bytes are rejected outright.
"""

import pickle

import ml_dtypes
import numpy as np
import pytest

from penroz_tpu.utils import checkpoint


def _roundtrip(data):
    return checkpoint._decode(checkpoint._encode(data))


def test_roundtrip_nested_tree_with_arrays():
    data = {
        "layers": [{"linear": {"in_features": 4, "out_features": 2}}],
        "params": {
            "layers.0.weight": np.arange(8, dtype=np.float32).reshape(4, 2),
            "layers.0.bias": np.zeros(2, dtype=ml_dtypes.bfloat16),
        },
        "opt_state_leaves": {0: np.int32(3), 1: np.ones(2, np.float64)},
        "status": {"code": "Trained", "message": None},
        "avg_cost": 1.5,
        "progress": [{"epoch": 0, "cost": 2.0, "ok": True}],
        "unicode": "penröz ✓",
    }
    out = _roundtrip(data)
    assert out["layers"] == data["layers"]
    np.testing.assert_array_equal(out["params"]["layers.0.weight"],
                                  data["params"]["layers.0.weight"])
    assert out["params"]["layers.0.bias"].dtype == ml_dtypes.bfloat16
    # int dict keys survive (JSON objects alone cannot express them)
    assert set(out["opt_state_leaves"]) == {0, 1}
    # numpy scalars come back as python scalars
    assert out["opt_state_leaves"][0] == 3
    assert out["status"] == data["status"]
    assert out["progress"] == data["progress"]
    assert out["unicode"] == data["unicode"]


def test_roundtrip_noncontiguous_and_empty_arrays():
    base = np.arange(24, dtype=np.float32).reshape(4, 6)
    data = {"t": base[:, ::2], "empty": np.zeros((0, 3), np.int8)}
    out = _roundtrip(data)
    np.testing.assert_array_equal(out["t"], base[:, ::2])
    assert out["empty"].shape == (0, 3)
    assert out["empty"].dtype == np.int8


def test_shard_pieces_shape_survives():
    """The shard-file payload shape: pieces are (ranges, array) pairs whose
    tuples become lists — reassembly unpacks them positionally."""
    data = {"tag": 7, "pieces": {"w": [(((0, 2), (0, 4)),
                                        np.ones((2, 4), np.float32))]}}
    out = _roundtrip(data)
    (ranges, arr), = out["pieces"]["w"]
    assert [tuple(r) for r in ranges] == [(0, 2), (0, 4)]
    np.testing.assert_array_equal(arr, np.ones((2, 4), np.float32))


def test_pickle_bytes_rejected():
    blob = pickle.dumps({"params": {}}, protocol=5)
    with pytest.raises(ValueError, match="bad magic"):
        checkpoint._decode(blob)


def test_payload_alignment():
    buf = checkpoint._encode({"a": np.ones(3, np.float32),
                              "b": np.ones(5, np.int8),
                              "c": np.ones(2, np.float32)})
    import json as _json
    import struct as _struct
    (hlen,) = _struct.unpack("<Q", buf[8:16])
    header = _json.loads(buf[16:16 + hlen])
    for m in header["arrays"]:
        assert m["offset"] % 64 == 0


def test_np_dtype_resolves_ml_dtypes_and_rejects_unknown():
    assert checkpoint.np_dtype("bfloat16") == np.dtype(ml_dtypes.bfloat16)
    assert checkpoint.np_dtype("float32") == np.dtype(np.float32)
    with pytest.raises(TypeError, match="unknown checkpoint dtype"):
        checkpoint.np_dtype("not_a_dtype")


def test_patch_meta_header_only_rewrite(tmp_path, monkeypatch):
    """patch_meta must update metadata fields and stream the array payload
    through byte-identically, without ever decoding it."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(checkpoint, "SHM_PATH", str(tmp_path / "shm"))
    arr = np.arange(64, dtype=np.float32).reshape(8, 8)
    checkpoint.save("pm", {"status": {"code": "Training", "message": None},
                           "params": {"w": arr}, "progress": [1, 2]},
                    sync_flush=True)
    checkpoint.patch_meta("pm", {"status": {"code": "Error",
                                            "message": "interrupted"}})
    out = checkpoint.load("pm")
    assert out["status"] == {"code": "Error", "message": "interrupted"}
    assert out["progress"] == [1, 2]
    np.testing.assert_array_equal(out["params"]["w"], arr)
    # peek agrees and never touches arrays
    peek = checkpoint.peek_tree("pm")
    assert peek["status"]["code"] == "Error"
    assert peek["params"]["w"] is None
    # array-carrying updates are rejected
    with pytest.raises(ValueError, match="array-free"):
        checkpoint.patch_meta("pm", {"params": {"w": arr}})
    with pytest.raises(KeyError):
        checkpoint.patch_meta("nope", {"status": {}})


def _save_corruptible(tmp_path, monkeypatch, model_id="crc"):
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(checkpoint, "SHM_PATH", str(tmp_path / "shm"))
    arr = np.arange(256, dtype=np.float32).reshape(16, 16)
    checkpoint.save(model_id, {"status": {"code": "Trained"},
                               "params": {"w": arr}}, sync_flush=True)
    return checkpoint.shm_model_path(model_id), arr


def test_corrupt_checkpoint_bit_flip_named_in_error(tmp_path, monkeypatch):
    """A single flipped payload byte must fail the per-stream CRC32 with
    the file path and the offending stream named — never a silent garbage
    decode into live weights."""
    path, arr = _save_corruptible(tmp_path, monkeypatch)
    blob = bytearray(open(path, "rb").read())
    blob[-3] ^= 0x40  # one bit, deep in the array payload
    with open(path, "wb") as f:
        f.write(blob)
    with pytest.raises(ValueError) as exc:
        checkpoint.load("crc")
    msg = str(exc.value)
    assert "CRC32 mismatch" in msg
    assert path in msg                 # which file
    assert "array stream 0" in msg     # which stream
    assert "float32" in msg


def test_truncated_checkpoint_named_in_error(tmp_path, monkeypatch):
    """A truncated container (killed copy, full disk) raises a descriptive
    truncation error instead of a bare struct/frombuffer error."""
    path, arr = _save_corruptible(tmp_path, monkeypatch, "trunc")
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) - arr.nbytes // 2])
    with pytest.raises(ValueError) as exc:
        checkpoint.load("trunc")
    msg = str(exc.value)
    assert "truncated" in msg
    assert path in msg
    assert "array stream 0" in msg


def test_pre_crc_checkpoints_still_load(tmp_path, monkeypatch):
    """Checkpoints written before the CRC field existed (no "crc32" in the
    array meta) must keep loading — verification is opportunistic."""
    import json as _json
    import struct as _struct
    buf = checkpoint._encode({"w": np.arange(8, dtype=np.int32)})
    (hlen,) = _struct.unpack("<Q", buf[8:16])
    header = _json.loads(buf[16:16 + hlen])
    for m in header["arrays"]:
        del m["crc32"]
    new_header = _json.dumps(header, separators=(",", ":")).encode()
    legacy = (buf[:8] + _struct.pack("<Q", len(new_header)) + new_header
              + buf[16 + hlen:])
    out = checkpoint._decode(legacy)
    np.testing.assert_array_equal(out["w"], np.arange(8, dtype=np.int32))


def test_list_model_ids_shard_suffix_only(tmp_path, monkeypatch):
    """Only the exact '.shard<idx>' suffix marks a shard file; a model id
    that merely contains '.shard' must stay visible."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(checkpoint, "SHM_PATH", str(tmp_path / "shm"))
    for mid in ("plain", "v1.sharded", "odd.shard"):
        checkpoint.save(mid, {"status": {"code": "Created"}},
                        sync_flush=True)
    checkpoint.save_shard("plain", 1, {"tag": 0, "pieces": {}},
                          sync_flush=True)
    assert checkpoint.list_model_ids() == ["odd.shard", "plain", "v1.sharded"]


def test_page_blob_save_load_delete(tmp_path, monkeypatch):
    """Disaggregated-prefill transport: a staged page blob round-trips
    arrays and scalar leaves through the CRC-checked container, load of a
    missing id is a typed KeyError, and delete is idempotent."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(checkpoint, "SHM_PATH", str(tmp_path / "shm"))
    blob = {"page_size": 4, "pages": 2, "length": 7, "quantized": False,
            "first_token": 42,
            "k": [np.arange(32, dtype=np.float32).reshape(2, 16)],
            "v": [np.arange(32, 64, dtype=np.float32).reshape(2, 16)]}
    checkpoint.save_page_blob("h1", blob)
    out = checkpoint.load_page_blob("h1")
    assert out["page_size"] == 4 and out["length"] == 7
    assert out["first_token"] == 42 and out["quantized"] is False
    np.testing.assert_array_equal(out["k"][0], blob["k"][0])
    np.testing.assert_array_equal(out["v"][0], blob["v"][0])
    assert checkpoint.delete_page_blob("h1") is True
    assert checkpoint.delete_page_blob("h1") is False   # idempotent
    with pytest.raises(KeyError, match="h1"):
        checkpoint.load_page_blob("h1")
