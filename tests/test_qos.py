"""Multi-tenant QoS tests (serve/qos.py + the scheduler's WFQ admission,
per-tenant token quotas, and preempt-to-prefix-cache resume).

Tier-1-safe: CPU, small shapes, no `slow` marker.  The load-bearing
contracts:

- WFQ: an interactive backlog drains ahead of a batch flood in weight
  proportion; default traffic (no priority, no tenant) stays exact FIFO.
- Quotas: an exhausted tenant's NEW admissions 429 with a refill-derived
  Retry-After while a victim tenant on the same engine is untouched.
- Preemption: a preempted-then-resumed request is greedy token-identical
  to an unpreempted run (across int8 × superstep × LoRA), the resume
  recomputes zero cached prompt tokens (``preempted_resume_cached_tokens``),
  and a crash injected at ``qos.preempt`` recovers with no leaked radix
  pins.
"""

import asyncio
import json
import math
import queue
import threading
import time

import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

# CI tier: heavier compiles (serving stack), same tier as test_app.
pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}


@pytest.fixture(autouse=True)
def _qos_state(workdir):
    """Fresh engine registry, fault counters, quota buckets, and underflow
    counters per test — all of them are process-wide by design."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import decode_scheduler, qos
    from penroz_tpu.serve import metrics as serve_metrics
    from penroz_tpu.utils import faults, tracing
    faults.reset()
    tracing.reset()
    serve_metrics.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()
    yield
    decode_scheduler.reset()
    faults.reset()
    tracing.reset()
    serve_metrics.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("qosgpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def make_engine():
    from penroz_tpu.serve import decode_scheduler
    engines = []

    def build(*args, **kwargs):
        engine = decode_scheduler.DecodeEngine(*args, **kwargs)
        engines.append(engine)
        return engine

    yield build
    for engine in engines:
        engine.shutdown()


class _Collector:
    def __init__(self, prompt, label=None, order=None):
        self.q = queue.Queue()
        self.tokens = list(prompt)
        self.received = 0
        self.label = label
        self.order = order

    def on_event(self, kind, value):
        if kind == "done" and self.order is not None:
            self.order.append(self.label)
        self.q.put((kind, value))

    def result(self, timeout=180):
        deadline = time.monotonic() + timeout
        while True:
            kind, value = self.q.get(
                timeout=max(deadline - time.monotonic(), 0.1))
            if kind == "token":
                self.tokens.append(value)
                self.received += 1
            elif kind == "done":
                return self.tokens
            else:
                raise value


def _submit(engine, prompt, max_new, priority=None, tenant=None,
            adapter=None, label=None, order=None):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt, label=label, order=order)
    engine.submit(decode_scheduler.Request(prompt, max_new, None,
                                           collector.on_event,
                                           adapter=adapter,
                                           priority=priority, tenant=tenant))
    return collector


def _wait_tokens(collector, n, timeout=120):
    deadline = time.monotonic() + timeout
    while collector.received < n:
        assert time.monotonic() < deadline, \
            f"only {collector.received}/{n} tokens arrived"
        try:
            kind, value = collector.q.get(timeout=1.0)
        except queue.Empty:
            continue
        assert kind == "token", (kind, value)
        collector.tokens.append(value)
        collector.received += 1


def _all_pins(cache) -> int:
    """Total live refcounts across every namespace of a radix cache."""
    total = 0
    stack = [nd for root in cache._roots.values()
             for nd in root.children.values()]
    while stack:
        nd = stack.pop()
        total += nd.refs
        stack.extend(nd.children.values())
    return total


# ---------------------------------------------------------------------------
# qos.py unit layer: priorities, tenants, WFQ drain order, quota buckets
# ---------------------------------------------------------------------------

def test_validate_priority_and_tenant_of():
    from penroz_tpu.serve import qos
    assert qos.validate_priority(None) == "standard"
    assert qos.validate_priority("interactive") == "interactive"
    with pytest.raises(ValueError, match="priority"):
        qos.validate_priority("urgent")
    # explicit tenant > adapter id > shared default
    assert qos.tenant_of("acme", "adapterX") == "acme"
    assert qos.tenant_of(None, "adapterX") == "adapterX"
    assert qos.tenant_of(None, None) == qos.DEFAULT_TENANT


def _mk_req(priority=None, tenant=None):
    from penroz_tpu.serve import decode_scheduler
    return decode_scheduler.Request([1], 1, None, lambda *a: None,
                                    priority=priority, tenant=tenant)


def test_wfq_weighted_drain_prefers_interactive(monkeypatch):
    """With the default 8/4/1 weights, a queued interactive burst drains
    ahead of a batch flood: after at most one batch pop (DRR cursor), every
    interactive request pops before the flood continues."""
    from penroz_tpu.serve import qos
    q = qos.WFQueue()
    for i in range(4):
        q.push(_mk_req(priority="batch", tenant="flood"))
    for i in range(3):
        q.push(_mk_req(priority="interactive", tenant="ui"))
    drained = [q.pop().priority for _ in range(7)]
    first_interactive = drained.index("interactive")
    assert first_interactive <= 1, drained
    # all interactive out before the flood's SECOND pop completes
    assert drained[first_interactive:first_interactive + 3] == \
        ["interactive"] * 3, drained
    assert len(q) == 0 and q.pop() is None


def test_wfq_default_traffic_is_exact_fifo():
    """No priority, no tenant → one sub-queue → byte-for-byte the old FIFO
    (the backward-compat clause)."""
    from penroz_tpu.serve import qos
    q = qos.WFQueue()
    reqs = [_mk_req() for _ in range(6)]
    for r in reqs:
        q.push(r)
    assert [q.pop() for _ in range(6)] == reqs
    # push_front requeues at the head of the sub-queue (preempt resume)
    a, b = _mk_req(), _mk_req()
    q.push(a)
    q.push_front(b)
    assert q.pop() is b and q.pop() is a


def test_wfq_weights_env_parsing(monkeypatch):
    from penroz_tpu.serve import qos
    monkeypatch.setenv("PENROZ_QOS_WEIGHTS", "interactive:12,batch:junk")
    w = qos.weights()
    assert w["interactive"] == 12
    assert w["batch"] >= 1          # junk falls back, never zero/negative
    monkeypatch.setenv("PENROZ_QOS_MAX_QUEUE_BATCH", "3")
    assert qos.class_queue_bound("batch") == 3
    assert qos.class_queue_bound("interactive") is None  # unset → aggregate


def test_wfq_class_tokens_tracks_queued_prompt_tokens():
    """class_tokens(cls) is the sum of queued prompt lengths per class —
    the router's least-loaded scoring reads it so a queue of three 8k
    prompts outweighs a queue of five 3-token prompts.  Every mutation
    path (push, push_front, pop, purge via _take, drain) keeps it exact."""
    from penroz_tpu.serve import decode_scheduler, qos

    def mk(n_tokens, priority=None):
        return decode_scheduler.Request(list(range(1, n_tokens + 1)), 1,
                                        None, lambda *a: None,
                                        priority=priority)

    q = qos.WFQueue()
    assert q.class_tokens("standard") == 0
    q.push(mk(5))
    q.push(mk(7))
    q.push(mk(100, priority="batch"))
    assert q.class_tokens("standard") == 12
    assert q.class_tokens("batch") == 100
    q.push_front(mk(3))
    assert q.class_tokens("standard") == 15
    popped = q.pop()                      # head of standard: the 3-token
    assert len(popped.prompt) == 3
    assert q.class_tokens("standard") == 12
    # purge (deadline/cancel sweep) decrements exactly the dropped prompts
    stale = mk(9)
    stale.cancelled = True
    q.push(stale)
    assert q.class_tokens("standard") == 21
    dropped = q.purge(lambda r: r.cancelled)
    assert dropped == [stale]
    assert q.class_tokens("standard") == 12
    q.drain()
    assert all(q.class_tokens(c) == 0 for c in qos.PRIORITIES)


def test_quota_bucket_retry_after_tracks_refill(monkeypatch):
    """Satellite: the quota 429's Retry-After is the bucket's refill time
    (deficit / rate, ceil, clamped) — a deeper deficit means a longer
    hint, and a request after the hinted wait is admitted again."""
    from penroz_tpu.serve import qos
    quotas = qos.QuotaManager()
    quotas.set_rate("t", 2.0)
    quotas.admit("t")                       # burst available
    quotas.charge("t", 8)                   # tokens ≈ 2 - 8 = -6
    with pytest.raises(qos.TenantQuotaExceeded) as exc:
        quotas.admit("t")
    assert exc.value.tenant == "t"
    # deficit 6 + the 1-token headroom, rate 2/s → ceil(7/2) = 4s
    assert exc.value.retry_after == 4
    quotas.charge("t", 20)                  # deepen the deficit
    with pytest.raises(qos.TenantQuotaExceeded) as deeper:
        quotas.admit("t")
    assert deeper.value.retry_after > exc.value.retry_after
    assert deeper.value.retry_after <= 60   # clamp
    # refill: simulate the wait by back-dating the bucket's clock
    bucket = quotas._buckets["t"]
    bucket.last -= 20.0                     # 20s ago → +40 tokens
    quotas.admit("t")                       # admitted again
    assert quotas.stats()["rejections"]["t"] == 2


def test_unpin_underflow_warns_once_and_counts():
    """Satellite: an unpaired unpin clamps to zero AND surfaces — one
    warning per distinct node key, every occurrence counted."""
    # capture on the module logger directly: an earlier suite test may
    # have applied dictConfig and cut propagation to caplog's root handler
    import logging
    from penroz_tpu.ops import kv_cache as KV
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = _Capture(level=logging.WARNING)
    logger = logging.getLogger("penroz_tpu.ops.kv_cache")
    old_level = logger.level
    logger.addHandler(handler)
    logger.setLevel(logging.WARNING)
    try:
        cache = KV.RadixPrefixCache(pages=[0, 1, 2, 3], page_size=2)
        cache.insert([1, 2, 3, 4])
        nodes = cache.match([1, 2, 3, 4])
        assert len(nodes) == 2
        cache.pin(nodes)
        cache.unpin(nodes)
        assert KV.unpin_underflow_count() == 0   # paired: no underflow
        cache.unpin(nodes)                   # unpaired: both nodes clamp
        cache.unpin(nodes[:1])               # same key again: no new warn
    finally:
        logger.removeHandler(handler)
        logger.setLevel(old_level)
    assert KV.unpin_underflow_count() == 3
    assert all(nd.refs == 0 for nd in nodes)
    warnings = [r for r in records
                if "unpin underflow" in r.getMessage()]
    assert len(warnings) == 2                # once per distinct key
    assert repr(nodes[0].key) in warnings[0].getMessage()


# ---------------------------------------------------------------------------
# engine layer: WFQ drain, per-class bounds, quotas, load-aware Retry-After
# ---------------------------------------------------------------------------

def test_queue_retry_after_scales_with_depth(gpt_model, make_engine):
    """Satellite: the queue-full Retry-After is depth × recent tick p50
    (clamped to [1, 30]) — not a static hint."""
    engine = make_engine("qosgpt", BLOCK, 0.0, None, capacity=1)
    with engine._cond:                     # worker provably parked out
        for _ in range(40):
            engine._h_tick.observe(2000.0)
        tick_p50 = engine._h_tick.quantile(0.5)
        assert tick_p50 >= 1000.0
        for n in (1, 5):
            while len(engine._pending) < n:
                engine._pending.push(_mk_req())
            expect = int(min(30, max(1, math.ceil(n * tick_p50 / 1000.0))))
            assert engine._queue_retry_after() == expect
        assert engine._queue_retry_after() > 1      # provably load-derived
        while len(engine._pending) < 100:
            engine._pending.push(_mk_req())
        assert engine._queue_retry_after() == 30    # clamp
        engine._pending.drain()


def test_interactive_backlog_outdrains_batch_flood(gpt_model, make_engine,
                                                   monkeypatch):
    """WFQ through the real engine: with one row and a queued batch flood
    + interactive pair, both interactive requests complete before the
    flood's second request — and every stream is greedy-exact."""
    from penroz_tpu.utils import faults
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@50")
    prompts = {"A": [1, 2, 3], "B1": [5], "B2": [6], "B3": [7],
               "I1": [9, 10], "I2": [11]}
    bases = {k: gpt_model.generate_tokens([p], BLOCK, 4, temperature=0.0)
             for k, p in prompts.items()}
    engine = make_engine("qosgpt", BLOCK, 0.0, None, capacity=1)
    order: list = []
    ca = _submit(engine, prompts["A"], 4, label="A", order=order)
    _wait_tokens(ca, 1)                       # A holds the row
    cs = {k: _submit(engine, prompts[k], 4, priority=pri, tenant=ten,
                     label=k, order=order)
          for k, pri, ten in (("B1", "batch", "flood"),
                              ("B2", "batch", "flood"),
                              ("B3", "batch", "flood"),
                              ("I1", "interactive", "ui"),
                              ("I2", "interactive", "ui"))}
    assert ca.result() == bases["A"]
    for k, c in cs.items():
        assert c.result() == bases[k], k
    assert order[0] == "A"
    # both interactives beat the flood's 2nd and 3rd requests
    assert order.index("I1") < order.index("B2")
    assert order.index("I2") < order.index("B2")
    stats = engine.stats()
    assert stats["admissions_by_class"] == {"interactive": 2, "standard": 1,
                                            "batch": 3}
    assert stats["queue_depth_by_class"] == {"interactive": 0, "standard": 0,
                                             "batch": 0}
    assert stats["ttft_ms_p99_by_class"]["interactive"] is not None


def test_per_class_bound_sheds_only_that_class(gpt_model, make_engine,
                                               monkeypatch):
    """PENROZ_QOS_MAX_QUEUE_BATCH bounds ONLY the batch sub-queues: a
    batch flood 429s at its bound while an interactive request still
    queues (and the error names the class)."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_QOS_MAX_QUEUE_BATCH", "1")
    monkeypatch.setenv(decode_scheduler.MAX_QUEUE_ENV, "8")  # roomy aggregate
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@60")
    base = {p: gpt_model.generate_tokens([list(p)], BLOCK, 3,
                                         temperature=0.0)
            for p in ((1, 2, 3), (5,), (9, 10))}
    engine = make_engine("qosgpt", BLOCK, 0.0, None, capacity=1)
    ca = _submit(engine, [1, 2, 3], 3)
    _wait_tokens(ca, 1)
    cb = _submit(engine, [5], 3, priority="batch")       # fills batch bound
    with pytest.raises(decode_scheduler.QueueFullError) as exc:
        _submit(engine, [6], 3, priority="batch")
    assert "batch" in str(exc.value)
    assert exc.value.retry_after >= 1
    # a DIFFERENT class still queues: the bound is per-class, not global
    ci = _submit(engine, [9, 10], 3, priority="interactive")
    assert ca.result() == base[(1, 2, 3)]
    assert cb.result() == base[(5,)]
    assert ci.result() == base[(9, 10)]
    assert engine.stats()["queue_rejections"] == 1


def test_quota_sheds_offender_only(gpt_model, make_engine, monkeypatch):
    """An exhausted tenant's NEXT admission 429s with a refill Retry-After
    while a victim tenant on the same engine admits and keeps greedy
    parity — and the offender's in-flight request was never touched."""
    from penroz_tpu.serve import decode_scheduler
    # near-zero refill: deterministic under CPU compile stalls (rate 4
    # would quietly refill the deficit away during a slow first request)
    monkeypatch.setenv("PENROZ_QOS_TENANT_TOKENS_PER_S", "0.05")
    prompt = [1, 2, 3]
    base = gpt_model.generate_tokens([prompt], BLOCK, 6, temperature=0.0)
    engine = make_engine("qosgpt", BLOCK, 0.0, None, capacity=2)
    # burst (min 1 token) admits the first request; prefill + emits then
    # charge 3 + 6 = 9 tokens, driving the bucket deep negative
    assert _submit(engine, prompt, 6, tenant="noisy").result() == base
    with pytest.raises(decode_scheduler.TenantQuotaExceeded) as exc:
        _submit(engine, prompt, 6, tenant="noisy")
    assert exc.value.tenant == "noisy"
    assert exc.value.retry_after >= 1
    # victim: same engine, own bucket — full parity, zero rejections
    assert _submit(engine, prompt, 6, tenant="victim").result() == base
    stats = engine.stats()
    assert stats["quota_rejections"] == 1
    # the stats view counts EMITTED tokens; the quota bucket additionally
    # billed each tenant's 3 prefilled prompt tokens
    assert stats["tenant_tokens"]["noisy"] == 6
    assert stats["tenant_tokens"]["victim"] == 6
    from penroz_tpu.serve import qos
    assert qos.QUOTAS.stats()["charged"] == {"noisy": 9, "victim": 9}


# ---------------------------------------------------------------------------
# preemption: evict-to-prefix-cache, zero-recompute resume, crash recovery
# ---------------------------------------------------------------------------

def _preempt_env(monkeypatch, superstep, int8):
    from penroz_tpu.serve import decode_scheduler
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "16")
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, str(superstep))
    if int8:
        monkeypatch.setenv("TURBO_QUANT_KV_CACHE", "1")


@pytest.mark.parametrize("int8,superstep", [
    pytest.param(0, 1, id="fp-1",
                 marks=pytest.mark.slow),  # fp step-1 covered by int8-1 arm
    pytest.param(0, 8, id="fp-8",
                 marks=pytest.mark.slow),  # fp step-8 covered by int8-8 arm
    pytest.param(1, 1, id="int8-1",
                 marks=pytest.mark.slow),  # step-1 seam covered elsewhere
    pytest.param(1, 8, id="int8-8")])
def test_preempt_resume_parity_matrix(gpt_model, make_engine, monkeypatch,
                                      superstep, int8):
    """THE acceptance matrix: a batch row evicted mid-generation for a
    queued interactive request resumes greedy token-identical to an
    unpreempted run (ONE uninterrupted stream), across int8 × superstep —
    with the cached prefix provably restored without recompute
    (``preempted_resume_cached_tokens``) and zero pins leaked."""
    from penroz_tpu.utils import faults
    _preempt_env(monkeypatch, superstep, int8)
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@150")
    pa, pb = [1, 2, 3, 4, 5, 6], [9, 10]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 10, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
    engine = make_engine("qosgpt", BLOCK, 0.0, None, capacity=1)
    ca = _submit(engine, pa, 10, priority="batch", tenant="flood")
    _wait_tokens(ca, 1)          # the victim provably holds the only row
    cb = _submit(engine, pb, 4, priority="interactive", tenant="ui")
    assert cb.result() == base_b
    assert ca.result() == base_a  # stream continuity across preempt+resume
    stats = engine.stats()
    assert stats["preemptions"] == 1
    # zero-recompute clause: the resume aliased ≥ 1 cached page back
    assert stats["preempted_resume_cached_tokens"] >= 4
    assert stats["preempted_resume_cached_tokens"] % 4 == 0  # whole pages
    assert stats["completed"] == 2
    assert engine.active_rows == 0
    assert _all_pins(engine._prefix_cache) == 0   # every pin released


# slow lane (tier1_budget): the preempt matrix [int8-8] and the LoRA
# crash-recovery tests keep both halves of this composition fast
@pytest.mark.slow
def test_preempt_resume_parity_with_lora_adapter(gpt_model, make_engine,
                                                 monkeypatch):
    """The mixed-LoRA clause: the victim decodes through a LoRA adapter —
    its eviction lands in the adapter-namespaced radix root, the base
    interactive request cannot alias it, and the resumed adapter stream
    stays token-identical to the unpreempted adapter run."""
    from penroz_tpu.models import lora
    from penroz_tpu.serve import adapters
    from penroz_tpu.utils import faults
    _preempt_env(monkeypatch, 1, 0)
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@150")
    cfg = lora.validate_config({"rank": 4})
    params = lora.init_params(gpt_model.arch, cfg, seed=7, init="random")
    lora.save_adapter("qten", "qosgpt", cfg, params, {"code": "Created"},
                      sync_flush=True)
    adapters.REGISTRY.reset()
    entry = adapters.REGISTRY.acquire("qten", "qosgpt")
    try:
        pa, pb = [1, 2, 3, 4, 5, 6], [9, 10]
        base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
        # unpreempted adapter oracle from an isolated engine
        iso = make_engine("qosgpt", BLOCK, 0.0, None, capacity=1)
        faults.reset()
        oracle = _submit(iso, pa, 8, adapter=entry).result()
        iso.shutdown()
        faults.reset()
        engine = make_engine("qosgpt", BLOCK, 0.0, None, capacity=1)
        ca = _submit(engine, pa, 8, priority="batch", adapter=entry)
        _wait_tokens(ca, 1)
        cb = _submit(engine, pb, 4, priority="interactive")
        assert cb.result() == base_b
        assert ca.result() == oracle
        stats = engine.stats()
        assert stats["preemptions"] == 1
        assert stats["preempted_resume_cached_tokens"] >= 4
        # the quota/tenant identity defaulted to the adapter id
        assert "qten" in stats["tenant_tokens"]
        assert _all_pins(engine._prefix_cache) == 0
    finally:
        adapters.REGISTRY.reset()


def test_preempt_crash_recovers_with_no_leaked_pins(gpt_model, make_engine,
                                                    monkeypatch):
    """Acceptance: a crash injected at ``qos.preempt`` fails the tick,
    ``_alloc_state`` rebuilds KV + a fresh radix cache (no pin can outlive
    the state it guards), and both replays are greedy-identical."""
    from penroz_tpu.utils import faults
    _preempt_env(monkeypatch, 1, 0)
    monkeypatch.setenv(faults.ENV,
                       "qos.preempt:raise@1,decode.step:sleep@120")
    pa, pb = [1, 2, 3, 4, 5, 6], [9, 10]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
    engine = make_engine("qosgpt", BLOCK, 0.0, None, capacity=1)
    ca = _submit(engine, pa, 8, priority="batch")
    _wait_tokens(ca, 1)
    cb = _submit(engine, pb, 4, priority="interactive")  # triggers preempt
    with pytest.raises(faults.InjectedFault):
        ca.result()
    with pytest.raises(faults.InjectedFault):
        cb.result()
    monkeypatch.setenv(faults.ENV, "")
    faults.reset()
    stats = engine.stats()
    assert stats["crashes_total"] == 1 and stats["engine_resets"] == 1
    assert stats["preemptions"] == 0        # the fault fired before any
    assert _all_pins(engine._prefix_cache) == 0
    # greedy-identical replays through the rebuilt engine
    assert _submit(engine, pa, 8, priority="batch").result() == base_a
    assert _submit(engine, pb, 4, priority="interactive").result() == base_b
    assert _all_pins(engine._prefix_cache) == 0


def test_preempt_disabled_env_queues_instead(gpt_model, make_engine,
                                             monkeypatch):
    """PENROZ_QOS_PREEMPT=0: the interactive request waits its WFQ turn —
    no eviction, victim runs to completion uninterrupted."""
    from penroz_tpu.utils import faults
    _preempt_env(monkeypatch, 1, 0)
    monkeypatch.setenv("PENROZ_QOS_PREEMPT", "0")
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@40")
    pa, pb = [1, 2, 3, 4, 5, 6], [9, 10]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 4, temperature=0.0)
    engine = make_engine("qosgpt", BLOCK, 0.0, None, capacity=1)
    ca = _submit(engine, pa, 8, priority="batch")
    _wait_tokens(ca, 1)
    cb = _submit(engine, pb, 4, priority="interactive")
    assert ca.result() == base_a
    assert cb.result() == base_b
    assert engine.stats()["preemptions"] == 0


# ---------------------------------------------------------------------------
# breaker half-open race (satellite)
# ---------------------------------------------------------------------------

def test_breaker_half_open_admits_exactly_one_probe(gpt_model, make_engine,
                                                    monkeypatch):
    """Satellite: N concurrent submits racing the cooldown expiry admit
    exactly ONE probe (the _cond-serialized _probe_inflight flag) — the
    rest 503 — and the probe's success closes the breaker."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    prompt = [1, 2, 3]
    base = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    monkeypatch.setenv(decode_scheduler.MAX_CRASHES_ENV, "1")
    monkeypatch.setenv(decode_scheduler.BREAKER_COOLDOWN_ENV, "300")
    monkeypatch.setenv(faults.ENV, "decode.step:raise@1")
    engine = make_engine("qosgpt", BLOCK, 0.0, None, capacity=2)
    with pytest.raises(faults.InjectedFault):
        _submit(engine, prompt, 4).result()
    assert engine.stats()["breaker_open"] is True
    monkeypatch.setenv(faults.ENV, "")
    faults.reset()
    time.sleep(0.4)                          # cooldown provably expired

    n = 8
    barrier = threading.Barrier(n)
    outcomes: list = [None] * n

    def racer(i):
        barrier.wait()
        try:
            outcomes[i] = _submit(engine, prompt, 4)
        except decode_scheduler.CircuitOpenError:
            outcomes[i] = "open"

    threads = [threading.Thread(target=racer, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    admitted = [o for o in outcomes if o != "open"]
    assert len(admitted) == 1, outcomes      # exactly one probe
    assert admitted[0].result() == base      # and it closes the breaker
    stats = engine.stats()
    assert stats["breaker_open"] is False
    assert stats["breaker_rejections"] == n - 1
    # breaker closed: everyone is admitted again
    assert _submit(engine, prompt, 4).result() == base


# ---------------------------------------------------------------------------
# HTTP layer: /tenants endpoints, shed-reason trace spans, underflow gauge
# ---------------------------------------------------------------------------

@pytest.fixture
def client(workdir):
    from penroz_tpu.serve import app as app_mod
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    from aiohttp.test_utils import TestClient, TestServer
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield client, loop
    loop.run_until_complete(client.close())
    loop.close()


def _request(client_loop, method, path, **kw):
    client, loop = client_loop

    async def go():
        resp = await client.request(method, path, **kw)
        body = await resp.read()
        return resp, body

    return loop.run_until_complete(go())


def _json(client_loop, method, path, **kw):
    resp, body = _request(client_loop, method, path, **kw)
    return resp.status, (json.loads(body) if body else None)


def _gen_payload(**overrides):
    payload = {"model_id": "qosgpt", "input": [[1, 2, 3]],
               "block_size": BLOCK, "max_new_tokens": 4, "temperature": 0.0}
    payload.update(overrides)
    return payload


def _trace_for(client, rid, timeout=10.0):
    deadline = time.monotonic() + timeout
    while True:
        status, tree = _json(client, "GET", f"/trace/{rid}")
        if status == 200 and tree["finished"]:
            return tree
        assert time.monotonic() < deadline, (status, tree)
        time.sleep(0.05)


def _span_names(span):
    return [c["name"] for c in span.get("children", [])]


def test_tenant_quota_endpoints_roundtrip(client):
    status, body = _json(client, "PUT", "/tenants/acme/quota",
                         json={"tokens_per_s": 5})
    assert status == 200
    assert body == {"tenant": "acme", "tokens_per_s": 5.0, "override": True,
                    "tier_bytes": 0.0}
    status, body = _json(client, "GET", "/tenants/")
    assert status == 200
    assert body["tenants"]["overrides"] == {"acme": 5.0}
    assert body["default_tokens_per_s"] == 0.0   # env default: disabled
    # null clears back to the env default
    status, body = _json(client, "PUT", "/tenants/acme/quota",
                         json={"tokens_per_s": None})
    assert status == 200
    assert body["override"] is False and body["tokens_per_s"] == 0.0
    # negative rate is a client error, not a silent clamp
    status, body = _json(client, "PUT", "/tenants/acme/quota",
                         json={"tokens_per_s": -1})
    assert status == 400
    status, body = _json(client, "GET", "/tenants/")
    assert body["tenants"]["overrides"] == {}


def test_trace_queue_shed_429(client, gpt_model, monkeypatch):
    """Satellite: a queue-full 429's trace ends 'queue_full' and still
    carries the queue-wait span + typed shed event."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(decode_scheduler.MAX_ROWS_ENV, "1")
    monkeypatch.setenv(decode_scheduler.MAX_QUEUE_ENV, "1")
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@80")
    test_client, loop = client

    async def go():
        task_a = asyncio.ensure_future(test_client.post(
            "/generate/", json=_gen_payload(max_new_tokens=8)))
        for _ in range(200):
            stats = await (await test_client.get("/serving_stats/")).json()
            if stats["active_rows"] >= 1 and stats["queue_depth"] == 0:
                break
            await asyncio.sleep(0.02)
        task_b = asyncio.ensure_future(test_client.post(
            "/generate/", json=_gen_payload(input=[[5]])))
        for _ in range(200):
            stats = await (await test_client.get("/serving_stats/")).json()
            if stats["queue_depth"] >= 1:
                break
            await asyncio.sleep(0.02)
        resp_c = await test_client.post(
            "/generate/", json=_gen_payload(input=[[7, 8]]))
        body_c = await resp_c.json()
        resp_a, resp_b = await task_a, await task_b
        return (resp_a.status, resp_b.status, resp_c.status, body_c,
                resp_c.headers.get("Retry-After"),
                resp_c.headers["X-Request-Id"])

    a_status, b_status, c_status, c_body, retry, rid = \
        loop.run_until_complete(go())
    assert (a_status, b_status, c_status) == (200, 200, 429), c_body
    assert retry is not None and int(retry) >= 1
    tree = _trace_for(client, rid)
    assert tree["meta"]["retire_reason"] == "queue_full"
    names = _span_names(tree["root"])
    assert "queue" in names and "shed" in names


def test_trace_quota_shed_429(client, gpt_model, monkeypatch):
    """Satellite: an exhausted tenant bucket 429s with a refill-derived
    Retry-After and a 'quota' retirement in the trace — while the same
    prompt under a different tenant still serves 200."""
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    # near-zero refill keeps the deficit deterministic under compile stalls
    status, _ = _json(client, "PUT", "/tenants/noisy/quota",
                      json={"tokens_per_s": 0.05})
    assert status == 200
    resp, body = _request(client, "POST", "/generate/",
                          json=_gen_payload(tenant="noisy"))
    assert resp.status == 200   # burst admits; charges 3 + 4 = 7 tokens
    resp, body = _request(client, "POST", "/generate/",
                          json=_gen_payload(tenant="noisy"))
    assert resp.status == 429
    detail = json.loads(body)["detail"]
    assert "noisy" in detail and "quota" in detail
    assert int(resp.headers["Retry-After"]) >= 1
    tree = _trace_for(client, resp.headers["X-Request-Id"])
    assert tree["meta"]["retire_reason"] == "quota"
    names = _span_names(tree["root"])
    assert "queue" in names and "shed" in names
    # the victim tenant is untouched
    resp, _ = _request(client, "POST", "/generate/",
                       json=_gen_payload(tenant="victim"))
    assert resp.status == 200
    _, stats = _json(client, "GET", "/serving_stats/")
    assert stats["quota_rejections"] == 1
    # emitted tokens per tenant (the quota bucket billed prompts on top)
    assert stats["tenant_tokens"] == {"noisy": 4, "victim": 4}


def test_trace_queued_deadline_504(client, gpt_model, monkeypatch):
    """Satellite: a request whose deadline expires while still QUEUED
    504s with a 'timeout' retirement and a queue span (it never reached
    prefill)."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(decode_scheduler.MAX_ROWS_ENV, "1")
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "1")
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@120")
    test_client, loop = client

    async def go():
        task_a = asyncio.ensure_future(test_client.post(
            "/generate/", json=_gen_payload(max_new_tokens=8)))
        for _ in range(200):
            stats = await (await test_client.get("/serving_stats/")).json()
            if stats["active_rows"] >= 1 and stats["queue_depth"] == 0:
                break
            await asyncio.sleep(0.02)
        resp_b = await test_client.post(
            "/generate/", json=_gen_payload(input=[[5]], timeout_ms=150))
        body_b = await resp_b.json()
        resp_a = await task_a
        return (resp_a.status, resp_b.status, body_b,
                resp_b.headers["X-Request-Id"])

    a_status, b_status, b_body, rid = loop.run_until_complete(go())
    monkeypatch.delenv(faults.ENV)
    faults.reset()
    assert a_status == 200
    assert b_status == 504, b_body
    assert "queued" in b_body["detail"]
    tree = _trace_for(client, rid)
    assert tree["meta"]["retire_reason"] == "timeout"
    names = _span_names(tree["root"])
    assert "queue" in names and "prefill" not in names


def test_metrics_exposes_unpin_underflow_gauge(client):
    from penroz_tpu.ops import kv_cache as KV
    resp, body = _request(client, "GET", "/metrics")
    assert b"penroz_prefix_cache_unpin_underflow 0" in body
    KV.record_unpin_underflow(("k", 1))
    resp, body = _request(client, "GET", "/metrics")
    assert b"penroz_prefix_cache_unpin_underflow 1" in body
