#!/bin/bash
# Bootstrap a venv and run the model service on localhost.
set -e
if [ ! -d ".venv" ]; then
    python3 -m venv .venv
fi
source .venv/bin/activate
pip install -e .
python -m penroz_tpu.serve.app
