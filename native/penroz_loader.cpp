// penroz_loader — native memory-mapped token-shard stream.
//
// The reference loads whole .npy shards with np.load and slices batches in
// Python (loaders.py:45-87).  This core instead mmaps every shard once and
// gathers batch windows straight from the page cache into a caller-provided
// int32 buffer — no per-shard heap copies, uint16→int32 widening in one
// vectorizable loop, shard-boundary stitching and end-of-stream wraparound
// handled natively, plus madvise(WILLNEED) prefetch for the next window so
// the kernel reads ahead while the accelerator computes.
//
// API (CPython extension, no pybind11):
//   Stream(shards: list[(path: str, data_offset: int, num_tokens: int)])
//     .total_tokens -> int
//     .gather_into(dest: writable buffer of int32, start: int, count: int)
//        # dest[0:count] = stream[(start + i) % total_tokens], widened
//     .prefetch(start: int, count: int)  # madvise readahead, non-blocking
//
// The .npy header is parsed by the Python wrapper (numpy's own reader);
// this core only needs (path, byte offset of the u2 payload, token count).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Shard {
  void* map = nullptr;
  size_t map_len = 0;
  const uint16_t* tokens = nullptr;  // payload view inside the mapping
  size_t num_tokens = 0;
};

struct StreamObject {
  PyObject_HEAD
  std::vector<Shard>* shards;
  std::vector<size_t>* prefix;  // prefix[i] = tokens before shard i
  size_t total;
};

void stream_dealloc(StreamObject* self) {
  if (self->shards) {
    for (Shard& s : *self->shards) {
      if (s.map && s.map != MAP_FAILED) munmap(s.map, s.map_len);
    }
    delete self->shards;
    delete self->prefix;
  }
  Py_TYPE(self)->tp_free(reinterpret_cast<PyObject*>(self));
}

int stream_init(StreamObject* self, PyObject* args, PyObject*) {
  PyObject* shard_list;
  if (!PyArg_ParseTuple(args, "O", &shard_list)) return -1;
  PyObject* seq = PySequence_Fast(shard_list, "expected a sequence");
  if (!seq) return -1;

  self->shards = new std::vector<Shard>();
  self->prefix = new std::vector<size_t>();
  self->total = 0;

  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    const char* path;
    unsigned long long offset, count;
    if (!PyArg_ParseTuple(item, "sKK", &path, &offset, &count)) {
      Py_DECREF(seq);
      return -1;
    }
    int fd = open(path, O_RDONLY);
    if (fd < 0) {
      PyErr_Format(PyExc_OSError, "cannot open shard %s", path);
      Py_DECREF(seq);
      return -1;
    }
    struct stat st;
    if (fstat(fd, &st) != 0 ||
        static_cast<unsigned long long>(st.st_size) < offset + count * 2) {
      close(fd);
      PyErr_Format(PyExc_ValueError, "shard %s smaller than declared", path);
      Py_DECREF(seq);
      return -1;
    }
    Shard s;
    s.map_len = offset + count * 2;
    s.map = mmap(nullptr, s.map_len, PROT_READ, MAP_PRIVATE, fd, 0);
    close(fd);  // mapping keeps its own reference
    if (s.map == MAP_FAILED) {
      PyErr_Format(PyExc_OSError, "mmap failed for %s", path);
      Py_DECREF(seq);
      return -1;
    }
    s.tokens = reinterpret_cast<const uint16_t*>(
        static_cast<const uint8_t*>(s.map) + offset);
    s.num_tokens = count;
    self->prefix->push_back(self->total);
    self->total += count;
    self->shards->push_back(s);
  }
  Py_DECREF(seq);
  if (self->total == 0) {
    PyErr_SetString(PyExc_ValueError, "stream has no tokens");
    return -1;
  }
  return 0;
}

// Locate the shard holding global position pos (pos < total).
inline size_t find_shard(const std::vector<size_t>& prefix, size_t pos) {
  size_t lo = 0, hi = prefix.size();
  while (hi - lo > 1) {
    size_t mid = (lo + hi) / 2;
    if (prefix[mid] <= pos) lo = mid; else hi = mid;
  }
  return lo;
}

PyObject* stream_gather_into(StreamObject* self, PyObject* args) {
  Py_buffer dest;
  unsigned long long start, count;
  if (!PyArg_ParseTuple(args, "w*KK", &dest, &start, &count)) return nullptr;
  // Reject counts whose byte size would overflow before the dest.len
  // comparison ("K" also silently wraps negative Python ints into huge
  // values) — an overflowed product would pass the check and the copy
  // loop would write far past the buffer.
  if (count > SIZE_MAX / sizeof(int32_t) ||
      count > static_cast<unsigned long long>(PY_SSIZE_T_MAX) /
                  sizeof(int32_t)) {
    PyBuffer_Release(&dest);
    PyErr_SetString(PyExc_ValueError, "count out of range");
    return nullptr;
  }
  if (dest.len < static_cast<Py_ssize_t>(count * sizeof(int32_t))) {
    PyBuffer_Release(&dest);
    PyErr_SetString(PyExc_ValueError, "destination buffer too small");
    return nullptr;
  }
  int32_t* out = static_cast<int32_t*>(dest.buf);
  size_t pos = start % self->total;
  size_t remaining = count;
  Py_BEGIN_ALLOW_THREADS
  while (remaining > 0) {
    size_t si = find_shard(*self->prefix, pos);
    const Shard& s = (*self->shards)[si];
    size_t local = pos - (*self->prefix)[si];
    size_t take = s.num_tokens - local;
    if (take > remaining) take = remaining;
    const uint16_t* src = s.tokens + local;
    for (size_t i = 0; i < take; i++) out[i] = src[i];
    out += take;
    remaining -= take;
    pos = (pos + take) % self->total;
  }
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&dest);
  Py_RETURN_NONE;
}

PyObject* stream_prefetch(StreamObject* self, PyObject* args) {
  unsigned long long start, count;
  if (!PyArg_ParseTuple(args, "KK", &start, &count)) return nullptr;
  size_t pos = start % self->total;
  size_t remaining = count;
  long page = sysconf(_SC_PAGESIZE);
  while (remaining > 0) {
    size_t si = find_shard(*self->prefix, pos);
    const Shard& s = (*self->shards)[si];
    size_t local = pos - (*self->prefix)[si];
    size_t take = s.num_tokens - local;
    if (take > remaining) take = remaining;
    const uint8_t* addr = reinterpret_cast<const uint8_t*>(s.tokens + local);
    uintptr_t base = reinterpret_cast<uintptr_t>(addr) & ~(page - 1);
    size_t len = reinterpret_cast<uintptr_t>(addr + take * 2) - base;
    madvise(reinterpret_cast<void*>(base), len, MADV_WILLNEED);
    remaining -= take;
    pos = (pos + take) % self->total;
  }
  Py_RETURN_NONE;
}

PyObject* stream_total(StreamObject* self, void*) {
  return PyLong_FromSize_t(self->total);
}

PyMethodDef stream_methods[] = {
    {"gather_into", reinterpret_cast<PyCFunction>(stream_gather_into),
     METH_VARARGS, "Fill an int32 buffer from the wrapped token stream."},
    {"prefetch", reinterpret_cast<PyCFunction>(stream_prefetch),
     METH_VARARGS, "madvise(WILLNEED) the pages backing a window."},
    {nullptr, nullptr, 0, nullptr}};

PyGetSetDef stream_getset[] = {
    {"total_tokens", reinterpret_cast<getter>(stream_total), nullptr,
     "Total tokens across all shards.", nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr}};

PyTypeObject StreamType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

PyModuleDef loader_module = {
    PyModuleDef_HEAD_INIT, "penroz_loader",
    "Memory-mapped token shard stream.", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit_penroz_loader() {
  StreamType.tp_name = "penroz_loader.Stream";
  StreamType.tp_basicsize = sizeof(StreamObject);
  StreamType.tp_flags = Py_TPFLAGS_DEFAULT;
  StreamType.tp_doc = "Memory-mapped multi-shard token stream.";
  StreamType.tp_new = PyType_GenericNew;
  StreamType.tp_init = reinterpret_cast<initproc>(stream_init);
  StreamType.tp_dealloc = reinterpret_cast<destructor>(stream_dealloc);
  StreamType.tp_methods = stream_methods;
  StreamType.tp_getset = stream_getset;
  if (PyType_Ready(&StreamType) < 0) return nullptr;
  PyObject* m = PyModule_Create(&loader_module);
  if (!m) return nullptr;
  Py_INCREF(&StreamType);
  PyModule_AddObject(m, "Stream", reinterpret_cast<PyObject*>(&StreamType));
  return m;
}
