// penroz_bpe — native byte-level BPE tokenizer core (trainer + encoder).
//
// The reference consumes BPE through tiktoken's Rust extension
// (gpt_tokenizers.py:10); this is the framework's own native equivalent so
// tokenization works offline and shard building is not bottlenecked on
// Python. Exposed as a plain CPython extension (no pybind11 dependency).
//
// Scheme ("penroz-bpe"): byte-level symbols (0..255), greedy lowest-rank
// merges; words are pre-split as {optional leading space}{letters} | digits |
// other-run, so encodings are stable across documents. Trained models are
// just the merge list in order.
//
// API:
//   train(corpus: bytes, num_merges: int) -> list[(int, int)]
//   Encoder(merges: list[(int, int)])
//     .encode(text: bytes) -> list[int]      # token ids
//     .decode(ids: list[int]) -> bytes
//     .vocab_size -> int

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

using Pair = std::pair<int, int>;

struct PairHash {
  size_t operator()(const Pair& p) const {
    return (static_cast<size_t>(p.first) << 32) ^
           static_cast<size_t>(static_cast<uint32_t>(p.second));
  }
};

// -------- word pre-splitting ------------------------------------------------

inline bool is_letter(uint8_t c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') || c >= 0x80;
}
inline bool is_digit(uint8_t c) { return c >= '0' && c <= '9'; }

// Split raw bytes into words: [space]letters+ | digits+ | single other.
std::vector<std::pair<size_t, size_t>> split_words(const uint8_t* data,
                                                   size_t len) {
  std::vector<std::pair<size_t, size_t>> words;
  size_t i = 0;
  while (i < len) {
    size_t start = i;
    size_t j = i;
    if (data[j] == ' ' && j + 1 < len && is_letter(data[j + 1])) j++;
    if (is_letter(data[j])) {
      while (j < len && is_letter(data[j])) j++;
      words.emplace_back(start, j - start);
      i = j;
    } else if (is_digit(data[j])) {
      while (j < len && is_digit(data[j])) j++;
      words.emplace_back(start, j - start);
      i = j;
    } else {
      words.emplace_back(start, 1);
      i = start + 1;
    }
  }
  return words;
}

// -------- training ----------------------------------------------------------

struct TrainWord {
  std::vector<int> syms;
  int64_t count = 0;
};

PyObject* bpe_train(PyObject*, PyObject* args) {
  Py_buffer corpus;
  long num_merges;
  if (!PyArg_ParseTuple(args, "y*l", &corpus, &num_merges)) return nullptr;
  const uint8_t* data = static_cast<const uint8_t*>(corpus.buf);
  size_t len = corpus.len;

  // Deduplicate words with counts.
  std::unordered_map<std::string, int64_t> word_counts;
  for (auto [off, wlen] : split_words(data, len)) {
    word_counts[std::string(reinterpret_cast<const char*>(data + off), wlen)]
        += 1;
  }
  PyBuffer_Release(&corpus);

  std::vector<TrainWord> words;
  words.reserve(word_counts.size());
  for (auto& [w, c] : word_counts) {
    TrainWord tw;
    tw.count = c;
    tw.syms.reserve(w.size());
    for (uint8_t b : w) tw.syms.push_back(b);
    words.push_back(std::move(tw));
  }

  // Pair counts + index of words containing each pair.
  std::unordered_map<Pair, int64_t, PairHash> pair_counts;
  std::unordered_map<Pair, std::unordered_set<size_t>, PairHash> pair_words;
  for (size_t wi = 0; wi < words.size(); wi++) {
    auto& syms = words[wi].syms;
    for (size_t k = 0; k + 1 < syms.size(); k++) {
      Pair p{syms[k], syms[k + 1]};
      pair_counts[p] += words[wi].count;
      pair_words[p].insert(wi);
    }
  }

  std::vector<Pair> merges;
  merges.reserve(num_merges);
  int next_id = 256;

  for (long m = 0; m < num_merges; m++) {
    // Highest-count pair (ties broken deterministically by pair value).
    Pair best{-1, -1};
    int64_t best_count = 0;
    for (auto& [p, c] : pair_counts) {
      if (c > best_count ||
          (c == best_count && best.first >= 0 && p < best)) {
        best = p;
        best_count = c;
      }
    }
    if (best_count < 2) break;  // nothing left worth merging

    int new_id = next_id++;
    merges.push_back(best);

    // Rewrite only the words that contain the merged pair.
    auto affected_it = pair_words.find(best);
    std::vector<size_t> affected(affected_it->second.begin(),
                                 affected_it->second.end());
    for (size_t wi : affected) {
      auto& syms = words[wi].syms;
      int64_t wc = words[wi].count;
      // remove old pair contributions of this word
      for (size_t k = 0; k + 1 < syms.size(); k++) {
        Pair p{syms[k], syms[k + 1]};
        auto it = pair_counts.find(p);
        if (it != pair_counts.end()) {
          it->second -= wc;
          if (it->second <= 0) pair_counts.erase(it);
        }
        auto pw = pair_words.find(p);
        if (pw != pair_words.end()) pw->second.erase(wi);
      }
      // apply the merge
      std::vector<int> out;
      out.reserve(syms.size());
      for (size_t k = 0; k < syms.size();) {
        if (k + 1 < syms.size() && syms[k] == best.first &&
            syms[k + 1] == best.second) {
          out.push_back(new_id);
          k += 2;
        } else {
          out.push_back(syms[k]);
          k += 1;
        }
      }
      syms = std::move(out);
      // add new pair contributions
      for (size_t k = 0; k + 1 < syms.size(); k++) {
        Pair p{syms[k], syms[k + 1]};
        pair_counts[p] += wc;
        pair_words[p].insert(wi);
      }
    }
  }

  PyObject* result = PyList_New(merges.size());
  for (size_t i = 0; i < merges.size(); i++) {
    PyList_SET_ITEM(result, i,
                    Py_BuildValue("(ii)", merges[i].first, merges[i].second));
  }
  return result;
}

// -------- encoder -----------------------------------------------------------

struct EncoderObject {
  PyObject_HEAD
  std::unordered_map<Pair, int, PairHash>* ranks;     // pair -> rank
  std::unordered_map<Pair, int, PairHash>* pair_ids;  // pair -> merged id
  std::vector<std::string>* vocab;                    // id -> bytes
};

void encoder_dealloc(PyObject* self) {
  auto* enc = reinterpret_cast<EncoderObject*>(self);
  delete enc->ranks;
  delete enc->pair_ids;
  delete enc->vocab;
  Py_TYPE(self)->tp_free(self);
}

int encoder_init(PyObject* self, PyObject* args, PyObject*) {
  PyObject* merges;
  if (!PyArg_ParseTuple(args, "O", &merges)) return -1;
  auto* enc = reinterpret_cast<EncoderObject*>(self);
  enc->ranks = new std::unordered_map<Pair, int, PairHash>();
  enc->pair_ids = new std::unordered_map<Pair, int, PairHash>();
  enc->vocab = new std::vector<std::string>();
  enc->vocab->reserve(256 + PySequence_Size(merges));
  for (int b = 0; b < 256; b++)
    enc->vocab->push_back(std::string(1, static_cast<char>(b)));

  PyObject* seq = PySequence_Fast(merges, "merges must be a sequence");
  if (!seq) return -1;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    int a, b;
    if (!PyArg_ParseTuple(item, "ii", &a, &b)) {
      Py_DECREF(seq);
      return -1;
    }
    Pair p{a, b};
    int id = 256 + static_cast<int>(i);
    (*enc->ranks)[p] = static_cast<int>(i);
    (*enc->pair_ids)[p] = id;
    enc->vocab->push_back((*enc->vocab)[a] + (*enc->vocab)[b]);
  }
  Py_DECREF(seq);
  return 0;
}

void encode_word(const EncoderObject* enc, const uint8_t* data, size_t len,
                 std::vector<int>& out) {
  std::vector<int> syms;
  syms.reserve(len);
  for (size_t i = 0; i < len; i++) syms.push_back(data[i]);
  // Greedy lowest-rank merging.
  while (syms.size() >= 2) {
    int best_rank = INT32_MAX;
    size_t best_pos = 0;
    for (size_t k = 0; k + 1 < syms.size(); k++) {
      auto it = enc->ranks->find({syms[k], syms[k + 1]});
      if (it != enc->ranks->end() && it->second < best_rank) {
        best_rank = it->second;
        best_pos = k;
      }
    }
    if (best_rank == INT32_MAX) break;
    Pair p{syms[best_pos], syms[best_pos + 1]};
    syms[best_pos] = enc->pair_ids->at(p);
    syms.erase(syms.begin() + best_pos + 1);
  }
  out.insert(out.end(), syms.begin(), syms.end());
}

PyObject* encoder_encode(PyObject* self, PyObject* args) {
  Py_buffer text;
  if (!PyArg_ParseTuple(args, "y*", &text)) return nullptr;
  auto* enc = reinterpret_cast<EncoderObject*>(self);
  const uint8_t* data = static_cast<const uint8_t*>(text.buf);
  std::vector<int> ids;
  ids.reserve(text.len / 3 + 4);
  for (auto [off, wlen] : split_words(data, text.len)) {
    encode_word(enc, data + off, wlen, ids);
  }
  PyBuffer_Release(&text);
  PyObject* result = PyList_New(ids.size());
  for (size_t i = 0; i < ids.size(); i++) {
    PyList_SET_ITEM(result, i, PyLong_FromLong(ids[i]));
  }
  return result;
}

PyObject* encoder_decode(PyObject* self, PyObject* args) {
  PyObject* ids;
  if (!PyArg_ParseTuple(args, "O", &ids)) return nullptr;
  auto* enc = reinterpret_cast<EncoderObject*>(self);
  PyObject* seq = PySequence_Fast(ids, "ids must be a sequence");
  if (!seq) return nullptr;
  std::string out;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    long id = PyLong_AsLong(PySequence_Fast_GET_ITEM(seq, i));
    if (id >= 0 && static_cast<size_t>(id) < enc->vocab->size()) {
      out += (*enc->vocab)[id];
    }
  }
  Py_DECREF(seq);
  return PyBytes_FromStringAndSize(out.data(), out.size());
}

PyObject* encoder_vocab_size(PyObject* self, void*) {
  auto* enc = reinterpret_cast<EncoderObject*>(self);
  return PyLong_FromSize_t(enc->vocab->size());
}

PyMethodDef encoder_methods[] = {
    {"encode", encoder_encode, METH_VARARGS, "encode(bytes) -> list[int]"},
    {"decode", encoder_decode, METH_VARARGS, "decode(list[int]) -> bytes"},
    {nullptr, nullptr, 0, nullptr},
};

PyGetSetDef encoder_getset[] = {
    {"vocab_size", encoder_vocab_size, nullptr, "total vocabulary size",
     nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr},
};

PyType_Slot encoder_slots[] = {
    {Py_tp_init, reinterpret_cast<void*>(encoder_init)},
    {Py_tp_dealloc, reinterpret_cast<void*>(encoder_dealloc)},
    {Py_tp_methods, encoder_methods},
    {Py_tp_getset, encoder_getset},
    {Py_tp_new, reinterpret_cast<void*>(PyType_GenericNew)},
    {0, nullptr},
};

PyType_Spec encoder_spec = {
    "penroz_bpe.Encoder", sizeof(EncoderObject), 0,
    Py_TPFLAGS_DEFAULT, encoder_slots,
};

PyMethodDef module_methods[] = {
    {"train", bpe_train, METH_VARARGS,
     "train(corpus: bytes, num_merges: int) -> list[(int, int)]"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module_def = {
    PyModuleDef_HEAD_INIT, "penroz_bpe",
    "Native byte-level BPE tokenizer core", -1, module_methods,
};

}  // namespace

PyMODINIT_FUNC PyInit_penroz_bpe() {
  PyObject* mod = PyModule_Create(&module_def);
  if (!mod) return nullptr;
  PyObject* encoder_type = PyType_FromSpec(&encoder_spec);
  if (!encoder_type || PyModule_AddObject(mod, "Encoder", encoder_type) < 0) {
    Py_XDECREF(encoder_type);
    Py_DECREF(mod);
    return nullptr;
  }
  return mod;
}
