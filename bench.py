"""Benchmark: GPT-2 124M training throughput (tokens/sec/chip + MFU) and
single-prompt decode TTFT on the default accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": ..., "vs_baseline": ...}

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
reported against the driver's north-star target of 35% MFU on the /train/
path: vs_baseline = measured_MFU / 0.35.
"""

from __future__ import annotations

import json
import os
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

PARTIAL_PATH = os.environ.get("PENROZ_BENCH_PARTIAL", "BENCH_PARTIAL.json")
_partial: dict = {}


def seed_partial(smoke: bool):
    """Seed from a previous attempt's file so a retrying watcher loop can
    only ever ADD metrics: run 1 capturing the headline MFU then dying
    mid-decode must not have run 2's first emit() clobber the file down to
    {device}.  Smoke runs neither seed nor get seeded from — their numbers
    are meaningless and must not brand (or be branded by) real-chip
    metrics.  ``resumed_keys`` lists the metrics still carried from the
    prior attempt; emit() retires entries as fresh values land, so a fully
    successful run reports no residue."""
    global PARTIAL_PATH
    if smoke:
        # Write direction too: a smoke run must never clobber a real prior
        # attempt's partial metrics sitting at the default path.
        if "PENROZ_BENCH_PARTIAL" not in os.environ:
            PARTIAL_PATH = "BENCH_PARTIAL.smoke.json"
        return
    if not os.path.exists(PARTIAL_PATH):
        return
    try:
        with open(PARTIAL_PATH) as fh:
            prior = json.load(fh)
    except (OSError, ValueError):
        return
    if not isinstance(prior, dict) or prior.get("smoke"):
        return
    prior.pop("resumed_keys", None)
    prior.pop("resumed_partial", None)  # legacy pre-resumed_keys flag
    _partial.update(prior)
    _partial["resumed_keys"] = sorted(prior)


def emit(**metrics):
    """Write each metric to ``BENCH_PARTIAL.json`` the moment its phase
    completes.  Round-3's bench printed one line at the very end after ~7
    sequential phases; a pool that answered probes but died mid-run lost
    every number (BENCH_r03.json rc=3).  With per-phase flushes, a pool
    that lives five minutes still yields the headline metrics."""
    import sys
    fresh = {k: v for k, v in metrics.items() if v is not None}
    _partial.update(fresh)
    if "resumed_keys" in _partial:
        left = [k for k in _partial["resumed_keys"] if k not in fresh]
        if left:
            _partial["resumed_keys"] = left
        else:
            del _partial["resumed_keys"]
    tmp = PARTIAL_PATH + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(_partial, fh, indent=1, sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, PARTIAL_PATH)
    keys = ", ".join(sorted(metrics))
    print(f"bench: phase done -> {keys}", file=sys.stderr, flush=True)


def _flops_per_token(n_matmul_params: int, depth: int, d_model: int,
                     seq: int) -> float:
    """Forward+backward FLOPs per trained token (nanoGPT/PaLM accounting).

    ``n_matmul_params`` excludes embedding-table lookups (wte/wpe) — only
    params that participate in matmuls count toward 6N."""
    return 6.0 * n_matmul_params + 12.0 * depth * d_model * seq


def peak_flops(device) -> float:
    """bf16 peak FLOPs/s for the benchmark chip."""
    kind = getattr(device, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v4" in kind:
        return 275e12
    if "v6" in kind:
        return 918e12
    return 197e12  # conservative default


def bench_train(arch, mapper, params, batch=8, block=1024, steps_per_call=4,
                warmup=2, timed=6, remat=False, buffers=None):
    import optax
    optimizer = mapper.to_optimizer()
    opt_state = optimizer.init(params)
    # Steady-state variant: /train/ computes the update-ratio stds only on
    # progress-sampled epochs (1 in epochs//100), so the hot loop skips them.
    epoch_fn = arch.train_epoch_fn(mapper.optimizer, steps_per_call, remat,
                                   jnp.bfloat16, with_ratios=False)
    rng = jax.random.key(0)
    data_rng = np.random.default_rng(0)
    x = jnp.asarray(data_rng.integers(0, 50304, (steps_per_call, batch, block),
                                      dtype=np.int32))
    y = jnp.asarray(data_rng.integers(0, 50304, (steps_per_call, batch, block),
                                      dtype=np.int32))
    buffers = buffers or {}

    for _ in range(warmup):
        params, opt_state, buffers, cost, _ = epoch_fn(params, opt_state,
                                                       buffers, x, y, rng)
    float(cost)  # host transfer: block_until_ready is unreliable over relay

    t0 = time.perf_counter()
    for _ in range(timed):
        params, opt_state, buffers, cost, _ = epoch_fn(params, opt_state,
                                                       buffers, x, y, rng)
    last_cost = float(cost)
    elapsed = time.perf_counter() - t0
    tokens = timed * steps_per_call * batch * block
    return tokens / elapsed, last_cost


def bench_ttft(arch, params, block=1024, prompt_len=128, trials=10,
               per_trial_priority=False):
    """p50 time-to-first-token: prefill(prompt) + sample, steady state.

    ``per_trial_priority=True``: each timed decode individually marks
    itself in flight (models.model.decode_priority) — the production
    shape, where priority is held per request, NOT across the whole
    benchmark (which would park a background trainer continuously and
    measure near-idle TTFT)."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    from penroz_tpu.ops import kv_cache as KV

    model = NeuralNetworkModel.__new__(NeuralNetworkModel)
    model.params = params
    model.buffers = {}
    model.arch = arch
    model.device = None
    model._sample_rng = jax.random.key(0)

    specs = model._kv_specs(1, prompt_len)
    decode = arch.decode_fn()
    compute_dtype = jnp.bfloat16
    prompt = jnp.asarray(np.random.default_rng(0).integers(
        0, 50304, (1, prompt_len), dtype=np.int32))
    temp = jnp.asarray(1.0, jnp.float32)

    import contextlib
    if per_trial_priority:
        from penroz_tpu.models import model as model_mod
        priority = model_mod.decode_priority
    else:
        priority = contextlib.nullcontext

    times = []
    for i in range(trials + 2):
        kv = KV.create_kv_state(specs, 1, block, model.dtype)
        rng = jax.random.key(i)
        with priority():
            t0 = time.perf_counter()
            tok, kv = decode(model.params, model.buffers, kv, prompt, rng,
                             temp, compute_dtype=compute_dtype, greedy=False,
                             top_k=None)
            int(np.asarray(tok)[0, 0])  # host transfer forces execution
            times.append((time.perf_counter() - t0) * 1000)
    return statistics.median(times[2:])  # drop compile/warmup trials


def bench_ttft_under_train(arch, params, mapper, block=1024, trials=8,
                           train_batch=8, train_steps=4):
    """p50 TTFT of a decode issued while a training epoch loop occupies the
    same chip — the serving-under-training case: the API process trains and
    serves on one device (serve/app.py runs both through its executor), so
    a /generate/ arriving mid-/train/ waits for the in-flight epoch
    program.  Worst-case added latency is one epoch's device occupancy;
    this measures the realized p50, not the bound.  The trainer thread uses
    its own params/optimizer state, mirroring the server (generate
    deserializes the checkpoint, it never shares the training params)."""
    import threading

    t_params, t_bufs = mapper.init_params(arch.mods, seed=1)
    optimizer = mapper.to_optimizer()
    opt_state = optimizer.init(t_params)
    epoch_fn = arch.train_epoch_fn(mapper.optimizer, train_steps, False,
                                   jnp.bfloat16, with_ratios=False)
    data_rng = np.random.default_rng(1)
    x = jnp.asarray(data_rng.integers(
        0, 50304, (train_steps, train_batch, block), dtype=np.int32))
    y = jnp.asarray(data_rng.integers(
        0, 50304, (train_steps, train_batch, block), dtype=np.int32))
    rng = jax.random.key(1)
    # compile the epoch program before the contention window opens
    t_params, opt_state, t_bufs, cost, _ = epoch_fn(t_params, opt_state,
                                                    t_bufs, x, y, rng)
    float(cost)
    priority_enabled = float(os.environ.get("PENROZ_DECODE_PRIORITY_MS",
                                            "1000")) > 0
    micro_fn = finalize_fn = None
    if priority_enabled:
        micro_fn, finalize_fn = arch.train_micro_fns(
            mapper.optimizer, train_steps, False, jnp.bfloat16,
            with_ratios=False)
        # compile the chunked programs too (one micro + finalize) so the
        # priority path never pays a trace inside the timed window; the
        # priority-off A/B run skips both compiles (unreachable branch)
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             t_params)
        b0, g0, c0 = micro_fn(t_params, t_bufs, zeros,
                              jnp.zeros((), jnp.float32), x[0], y[0], rng, 0)
        t_params, opt_state, t_bufs, cost, _ = finalize_fn(
            t_params, opt_state, g0, b0, c0)
        float(cost)

    stop = threading.Event()
    died = []

    def trainer():
        nonlocal t_params, opt_state, t_bufs
        from penroz_tpu.models import model as model_mod
        priority_on = priority_enabled
        try:
            while not stop.is_set():
                # Decode-priority window, same rule as the real /train/
                # loop: queued decodes get the chip between epochs.
                model_mod._yield_to_decodes()
                if priority_on and model_mod.decode_pending() > 0:
                    # Micro-step granularity via the SAME driver the real
                    # /train/ loop uses (one device program per
                    # micro-step, priority window between each) so this
                    # benchmark measures the production policy, not a
                    # re-implementation of it.
                    t_params, opt_state, t_bufs, c, _ = \
                        model_mod.run_microstepped_epoch(
                            micro_fn, finalize_fn, t_params, opt_state,
                            t_bufs, x, y, rng, train_steps)
                else:
                    t_params, opt_state, t_bufs, c, _ = epoch_fn(
                        t_params, opt_state, t_bufs, x, y, rng)
                # One epoch in flight at a time, like the real /train/
                # loop (per-epoch progress bookkeeping syncs on the cost):
                # without this the thread enqueues an unbounded backlog
                # and the decode would starve behind it instead of
                # waiting <= 1 epoch.
                float(c)
        except Exception as exc:  # noqa: BLE001 — surfaced via `died`
            died.append(exc)

    th = threading.Thread(target=trainer, name="bench-train-bg")
    th.start()
    try:
        ttft = bench_ttft(arch, params, block=block, trials=trials,
                          per_trial_priority=True)
    finally:
        stop.set()
        th.join()
    if died:
        # The contention never (fully) happened — reporting this TTFT as
        # "under train" would be an invisibly wrong idle number.
        import sys
        print(f"bench: background trainer died ({died[0]!r}); dropping "
              f"ttft_under_train", file=sys.stderr, flush=True)
        return None
    return ttft


def bench_decode_throughput(arch, params, mapper, block=1024, tokens=96):
    """Steady-state single-stream decode tokens/sec via the chunked path."""
    from penroz_tpu.models.model import NeuralNetworkModel
    model = NeuralNetworkModel.__new__(NeuralNetworkModel)
    model.params = params
    model.buffers = {}
    model.arch = arch
    model.device = None
    model._sample_rng = jax.random.key(0)
    prompt = [list(np.random.default_rng(0).integers(0, 50304, 128))]
    # warm with the same call so the exact chunk programs the timed run
    # dispatches (pow-2 ceiling of the tail) are already compiled
    model.generate_tokens(prompt, block, tokens, temperature=1.0)
    t0 = time.perf_counter()
    model.generate_tokens(prompt, block, tokens, temperature=1.0)
    return tokens / (time.perf_counter() - t0)


def bench_batched_decode(arch, params, block=1024, tokens=64, batch=8):
    """Aggregate tokens/sec of the ragged batched serving path
    (POST /generate_batch/, models/model.py::generate_tokens_batched):
    ``batch`` prompts of different lengths share one forward per step."""
    from penroz_tpu.models.model import NeuralNetworkModel
    model = NeuralNetworkModel.__new__(NeuralNetworkModel)
    model.params = params
    model.buffers = {}
    model.arch = arch
    model.device = None
    model._sample_rng = jax.random.key(0)
    model._pipe_layout = None
    rng = np.random.default_rng(0)
    # ragged lengths spanning 32..128 — the shape the feature exists for
    prompts = [list(rng.integers(0, 50304, int(n)))
               for n in np.linspace(32, 128, batch)]
    model.generate_tokens_batched(prompts, block, tokens, temperature=1.0)
    t0 = time.perf_counter()
    model.generate_tokens_batched(prompts, block, tokens, temperature=1.0)
    return batch * tokens / (time.perf_counter() - t0), batch


def bench_moe_dispatch(d=512, experts=8, top_k=2, depth=4, batch=8,
                       block=512, steps=2, timed=12):
    """Dense vs capacity-packed MoE dispatch on the same stack: tokens/sec
    each way.  Capacity dispatch computes only ``C = top_k·T/E·1.25``
    tokens per expert instead of all T per expert (ops/modules.py MoE) —
    this measures the realized speedup, not the claimed FLOP ratio.
    Returns (dense_tps, capacity_tps) or None on failure (showcase).

    ``timed=12``: each call is only ~80ms of device work at these shapes,
    and the relay's dispatch floor has been observed near 107ms — a short
    timed window buries the dense/capacity delta under transport RTT
    (r04's first capture read 0.996x where an amortized probe read 1.73x).
    """
    from __graft_entry__ import OPTIMIZER
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch

    def stack(dispatch):
        layers = [{"summation": [
            {"embedding": {"num_embeddings": 50304, "embedding_dim": d},
             "normal": {"mean": 0.0, "std": 0.02}},
            {"position": {"num_embeddings": block, "embedding_dim": d},
             "normal": {"mean": 0.0, "std": 0.02}}]}]
        layers += [{"residual": [
            {"sequential": [
                {"layernorm": {"normalized_shape": d}},
                {"linear": {"in_features": d, "out_features": 3 * d},
                 "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                {"attention": {"num_heads": 8, "dropout": 0.0}},
                {"linear": {"in_features": d, "out_features": d}}]},
            {"sequential": [
                {"layernorm": {"normalized_shape": d}},
                {"moe": {"in_features": d, "intermediate_size": 4 * d,
                         "num_experts": experts, "top_k": top_k,
                         "dispatch": dispatch}}]}]}
            for _ in range(depth)]
        layers += [{"layernorm": {"normalized_shape": d}},
                   {"linear": {"in_features": d, "out_features": 50304,
                               "bias": False}},
                   {"softmax": {"dim": -1}}]
        return layers

    try:
        out = []
        for dispatch in ("dense", "capacity"):
            mapper = Mapper(stack(dispatch), OPTIMIZER)
            arch = CompiledArch.get(mapper.layers)
            params, buffers = mapper.init_params(arch.mods, seed=0)
            tps, _ = bench_train(arch, mapper, params, batch=batch,
                                 block=block, steps_per_call=steps,
                                 warmup=2, timed=timed, buffers=buffers)
            out.append(tps)
        return tuple(out)
    except Exception as exc:  # noqa: BLE001 — optional showcase config
        import logging
        logging.getLogger(__name__).warning("MoE dispatch bench skipped: %s",
                                            exc)
        return None


def bench_paged_generate(arch, params, block=1024, tokens=64):
    """Paged-KV single-stream decode (BASELINE config "gpt2-medium
    /generate/ with paged KV"): tokens/sec through the paged pool +
    assigned page bytes at the end of the run.

    Page-size sweep (skip with PENROZ_BENCH_PAGED_SWEEP=0): r04 measured
    0.945x contiguous at the default page size; the last 5% is a
    page-granularity trade (smaller pages → more fetch dispatches,
    larger → more over-fetch), so let the chip pick among {default, 2x,
    4x} and report the winner + per-size results (``paged_sweep`` in the
    partial)."""
    import os

    from penroz_tpu.models.model import NeuralNetworkModel
    from penroz_tpu.ops import kv_cache as KV

    model = NeuralNetworkModel.__new__(NeuralNetworkModel)
    model.params = params
    model.buffers = {}
    model.arch = arch
    model.device = None
    model._sample_rng = jax.random.key(0)
    prompt = [list(np.random.default_rng(0).integers(0, 50304, 128))]

    def run_once():
        # warm with the same call shape (non-ramped) so the exact chunk
        # programs the timed run dispatches are already compiled
        for _ in model._generate_iter(list(prompt[0]), block, tokens, 1.0,
                                      None, None):
            pass
        metrics = KV.KVCache(len(arch.attn_layers))
        ctx = list(prompt[0])
        t0 = time.perf_counter()
        for _ in model._generate_iter(ctx, block, tokens, 1.0, None,
                                      metrics):
            pass
        tps = tokens / (time.perf_counter() - t0)
        st = getattr(metrics, "final_state", None)
        assigned = st.assigned_bytes() if hasattr(st, "assigned_bytes") else 0
        return tps, assigned

    os.environ[KV.PAGED_ENV] = "1"
    prev_page = os.environ.get(KV.PAGE_SIZE_ENV)
    try:
        base_page = KV.default_page_size()
        candidates = [base_page]
        if (os.environ.get("PENROZ_BENCH_PAGED_SWEEP", "1") == "1"
                and os.environ.get("PENROZ_BENCH_SMOKE") != "1"):
            candidates += [2 * base_page, 4 * base_page]
        best = None
        sweep = {}
        for page in candidates:
            os.environ[KV.PAGE_SIZE_ENV] = str(page)
            try:
                tps, assigned = run_once()
            except Exception as exc:  # noqa: BLE001 — skip bad page size
                import logging
                logging.getLogger(__name__).warning(
                    "paged sweep page_size=%d failed: %s", page, exc)
                continue
            sweep[f"page{page}"] = round(tps, 1)
            if len(candidates) > 1:
                emit(paged_sweep=dict(sweep))
            if best is None or tps > best[0]:
                best = (tps, assigned, page)
        if best is None:
            raise RuntimeError("every paged config failed")
        if len(candidates) > 1:
            emit(paged_page_size=best[2])
        return best[0], best[1]
    finally:
        os.environ.pop(KV.PAGED_ENV, None)
        if prev_page is None:
            os.environ.pop(KV.PAGE_SIZE_ENV, None)
        else:
            os.environ[KV.PAGE_SIZE_ENV] = prev_page


def bench_long_context(depth=12, d_model=768, block=4096, batch=1,
                       steps_per_call=2, timed=4, heads=12):
    """Long-context training throughput at T=4096 (flash fwd+bwd kernels
    stream K/V through the grid, so the (T,S) score matrix never
    materializes).  Runs WITHOUT remat first — at batch=1 the activations
    (~1.5 GB) fit v5e HBM comfortably, and the whole-loss checkpoint's
    forward replay was costing ~25% of the measured MFU (r04 first
    capture: 0.297 with remat vs 0.457 for the T=1024 headline) — and
    falls back to remat=True only if the no-remat compile/run fails
    (genuinely memory-bound configs).

    Capture-time tuning sweep (skip with PENROZ_BENCH_LONGCTX_SWEEP=0):
    probes flash block_q/block_k and batch variants with a short timed
    window each — a fresh ``CompiledArch`` per config, since the env
    knobs are read at trace time — then re-measures the winner with the
    full window.  The chip picks the config; per-config results land in
    the partial as ``long_ctx_sweep`` so a mid-run death still records
    what was learned.  Returns (tokens_per_sec, mfu, block, cfg_label)
    or None on any failure — this config is a showcase, not a gate."""
    from __graft_entry__ import OPTIMIZER
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch
    from penroz_tpu.models import presets
    import logging

    def run_cfg(bq, bk, b, tsteps, twarm, ttimed):
        os.environ["PENROZ_FLASH_BLOCK_Q"] = str(bq)
        os.environ["PENROZ_FLASH_BLOCK_K"] = str(bk)
        layers = presets.gpt2_custom(d=d_model, heads=heads, depth=depth,
                                     vocab=50304, block=block)
        mapper = Mapper(layers, OPTIMIZER)
        arch = CompiledArch(mapper.layers)  # fresh jit caches per config
        params, _ = mapper.init_params(arch.mods, seed=0)
        n_params = sum(int(np.prod(p.shape)) for p in params.values())
        n_matmul = n_params - sum(int(np.prod(p.shape))
                                  for k, p in params.items()
                                  if k.startswith("layers.0."))
        try:
            tps, _ = bench_train(arch, mapper, params, batch=b,
                                 block=block, steps_per_call=tsteps,
                                 warmup=twarm, timed=ttimed, remat=False)
        except Exception as no_remat_exc:  # noqa: BLE001 — OOM: pay replay
            logging.getLogger(__name__).warning(
                "long-context no-remat run failed (%s); retrying with "
                "remat", no_remat_exc)
            params, _ = mapper.init_params(arch.mods, seed=0)
            params = jax.device_put(params, jax.devices()[0])
            tps, _ = bench_train(arch, mapper, params, batch=b,
                                 block=block, steps_per_call=tsteps,
                                 warmup=twarm, timed=ttimed, remat=True)
        mfu = (tps * _flops_per_token(n_matmul, depth, d_model, block)
               / peak_flops(jax.devices()[0]))
        return tps, mfu

    prev_q = os.environ.get("PENROZ_FLASH_BLOCK_Q")
    prev_k = os.environ.get("PENROZ_FLASH_BLOCK_K")
    try:
        sweep_on = (os.environ.get("PENROZ_BENCH_LONGCTX_SWEEP", "1") == "1"
                    and os.environ.get("PENROZ_BENCH_SMOKE") != "1")

        def envint(name, default):
            try:
                return int(os.environ.get(name) or default)
            except ValueError:
                return default

        # Seed from the operator's pinned env config (sweep off / smoke:
        # honor it verbatim instead of clobbering it with literals).
        best = (envint("PENROZ_FLASH_BLOCK_Q", 512),
                envint("PENROZ_FLASH_BLOCK_K", 512), batch)
        if sweep_on:
            sweep = {}
            # (block_q, block_k, batch): env/defaults first, then narrower
            # q blocks (more grid parallelism for the dq pass), wider k
            # streams (fewer carry updates), and batch=2 (row headroom).
            cands = [best, (256, 512, batch), (512, 1024, batch),
                     (1024, 512, batch), (512, 512, 2 * batch)]
            seen = set()
            cands = [c for c in cands
                     if not (c in seen or seen.add(c))]
            for bq, bk, b in cands:
                try:
                    tps, mfu = run_cfg(bq, bk, b, tsteps=steps_per_call,
                                       twarm=1, ttimed=2)
                except Exception as exc:  # noqa: BLE001 — skip bad config
                    logging.getLogger(__name__).warning(
                        "long-ctx sweep config bq=%d bk=%d b=%d failed: %s",
                        bq, bk, b, exc)
                    continue
                sweep[f"bq{bq}_bk{bk}_b{b}"] = round(tps, 1)
                emit(long_ctx_sweep=dict(sweep))
                if tps > sweep.get(f"bq{best[0]}_bk{best[1]}_b{best[2]}",
                                   0.0):
                    best = (bq, bk, b)
        bq, bk, b = best
        tps, mfu = run_cfg(bq, bk, b, tsteps=steps_per_call, twarm=2,
                           ttimed=timed)
        return tps, mfu, block, f"bq{bq}_bk{bk}_b{b}"
    except Exception as exc:  # noqa: BLE001 — optional showcase config
        logging.getLogger(__name__).warning("long-context bench skipped: %s",
                                            exc)
        return None
    finally:
        for var, prev in (("PENROZ_FLASH_BLOCK_Q", prev_q),
                          ("PENROZ_FLASH_BLOCK_K", prev_k)):
            if prev is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = prev


def bench_dispatch_floor():
    """p50 latency of a trivial jitted call — the harness/relay floor that
    bounds TTFT and per-dispatch decode on remotely attached TPUs."""
    trivial = jax.jit(lambda x: x + 1)
    x = jnp.zeros((4,))
    np.asarray(trivial(x))
    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        np.asarray(trivial(x))
        times.append((time.perf_counter() - t0) * 1000)
    return statistics.median(times)


def _wait_for_backend() -> bool:
    """Survive a flaky accelerator pool: probe the backend in short-lived
    CHILD processes (a wedged in-process ``jax.devices()`` can never be
    retried — backend init poisons the caller) with exponential backoff
    until it answers or the total budget (``PENROZ_BENCH_WAIT_S``, default
    900 s) runs out.  Round-2's official bench died rc=3 on the first
    180 s relay outage (BENCH_r02.json); this keeps retrying through
    transient pool failures.

    Returns True when the accelerator answered.  On budget exhaustion the
    default is no longer a metric-less rc=3 (BENCH_r05.json: ``parsed:
    null`` after 900 s of probes): returns False so main() can fall back
    to a CPU-interop capture (tagged ``backend: cpu-fallback``) — the perf
    trajectory is never empty.  ``PENROZ_BENCH_CPU_FALLBACK=0`` restores
    the hard abort."""
    import os
    import subprocess
    import sys
    budget = float(os.environ.get("PENROZ_BENCH_WAIT_S", "900"))
    probe_timeout = float(os.environ.get("PENROZ_BENCH_PROBE_S", "150"))
    deadline = time.monotonic() + budget
    attempt = 0
    probe = ("import jax; d = jax.devices(); "
             "print('BACKEND_OK', d[0].device_kind, len(d), flush=True)")
    while True:
        attempt += 1
        try:
            out = subprocess.run([sys.executable, "-c", probe],
                                 capture_output=True, text=True,
                                 timeout=probe_timeout)
            if out.returncode == 0 and "BACKEND_OK" in out.stdout:
                print(f"bench: backend up (probe attempt {attempt}): "
                      f"{out.stdout.strip().split('BACKEND_OK ')[-1]}",
                      file=sys.stderr, flush=True)
                return True
            detail = (out.stderr or out.stdout).strip().splitlines()
            detail = detail[-1] if detail else f"rc={out.returncode}"
        except subprocess.TimeoutExpired:
            detail = f"probe timed out after {probe_timeout:.0f}s"
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            if os.environ.get("PENROZ_BENCH_CPU_FALLBACK", "1") != "0":
                print(f"bench: accelerator backend unreachable after "
                      f"{budget:.0f}s / {attempt} probe attempts (last: "
                      f"{detail}) — falling back to CPU-interop metrics",
                      file=sys.stderr, flush=True)
                return False
            print(f"bench: accelerator backend unreachable after "
                  f"{budget:.0f}s / {attempt} probe attempts (last: "
                  f"{detail}) — aborting without metrics",
                  file=sys.stderr, flush=True)
            os._exit(3)
        delay = min(min(2.0 ** attempt, 60.0), max(remaining, 1.0))
        print(f"bench: backend probe {attempt} failed ({detail}); "
              f"retrying in {delay:.0f}s ({remaining:.0f}s left)",
              file=sys.stderr, flush=True)
        time.sleep(delay)


def _enter_cpu_fallback():
    """Retarget the run at the in-process CPU backend and start a fresh
    partial: fallback numbers must not mix into (or clobber) a prior real
    chip capture sitting at the default partial path."""
    global PARTIAL_PATH
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    if "PENROZ_BENCH_PARTIAL" not in os.environ:
        PARTIAL_PATH = "BENCH_PARTIAL.cpu.json"
    _partial.clear()
    emit(backend="cpu-fallback")


def _devices_or_die(timeout_s: float = 300.0):
    """First in-process backend touch with a watchdog (after
    ``_wait_for_backend`` proved a child can attach): a wedged relay makes
    ``jax.devices()`` block forever, which would hang the whole bench run
    silently.  Fail fast with a diagnostic instead (stderr only — never
    emit a fake metrics line)."""
    import concurrent.futures
    import os
    import sys
    pool = concurrent.futures.ThreadPoolExecutor(1)
    fut = pool.submit(jax.devices)
    try:
        return fut.result(timeout=timeout_s)
    except concurrent.futures.TimeoutError:
        print(f"bench: accelerator backend unreachable after "
              f"{timeout_s:.0f}s (relay/pool down?) — aborting without "
              f"metrics", file=sys.stderr, flush=True)
        os._exit(3)  # the blocked worker thread cannot be joined


def main():
    from __graft_entry__ import OPTIMIZER, _gpt2_dsl
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch

    # PENROZ_BENCH_SMOKE=1: tiny shapes/counts so the whole phase pipeline
    # (ordering, partial emission, params re-init after donation) can be
    # validated on CPU without a chip.  Numbers produced under smoke are
    # meaningless and the artifact says so.
    smoke = os.environ.get("PENROZ_BENCH_SMOKE") == "1"
    seed_partial(smoke)
    cpu_fallback = not _wait_for_backend()
    if cpu_fallback:
        _enter_cpu_fallback()
    device = _devices_or_die()[0]
    # cpu-fallback runs the smoke shapes: the point is a non-empty
    # decode/prefill trajectory, not CPU-scale GPT-2 wall time.
    small = smoke or cpu_fallback
    depth, d_model, block = (2, 64, 256) if small else (12, 768, 1024)
    if smoke:
        emit(smoke=True)
    mapper = Mapper(_gpt2_dsl(depth=depth, d=d_model, block=block,
                              heads=4 if small else 12), OPTIMIZER)
    arch = CompiledArch.get(mapper.layers)
    params, _ = mapper.init_params(arch.mods, seed=0)
    params = jax.device_put(params, device)
    n_params = sum(int(np.prod(p.shape)) for p in params.values())
    # Embedding tables (layer 0 summation: wte + wpe) are lookups, not matmuls.
    n_matmul_params = n_params - sum(
        int(np.prod(p.shape)) for k, p in params.items()
        if k.startswith("layers.0."))
    emit(device=str(device.device_kind), n_params=n_params)

    # Headline phases first: a pool that dies mid-run must still yield the
    # numbers that matter (train MFU, then TTFT).  The train benchmark
    # donates (consumes) params; the decode phases re-init afterwards so
    # only one full parameter copy is ever resident.
    train_kw = (dict(batch=2, block=block, steps_per_call=2, warmup=1,
                     timed=2) if small else {})
    tokens_per_sec, cost = bench_train(arch, mapper, params, **train_kw)
    mfu = (tokens_per_sec
           * _flops_per_token(n_matmul_params, depth, d_model, block)
           / peak_flops(device))
    emit(value=round(tokens_per_sec, 1), mfu=round(mfu, 4),
         vs_baseline=round(mfu / 0.35, 3), train_cost_sample=round(cost, 3))

    params = jax.device_put(mapper.init_params(arch.mods, seed=0)[0], device)
    ttft_ms = bench_ttft(arch, params, block=block,
                         trials=3 if small else 10)
    emit(ttft_ms_p50=round(ttft_ms, 2))
    dispatch_floor = bench_dispatch_floor()
    emit(dispatch_floor_ms=round(dispatch_floor, 2))

    if cpu_fallback:
        # Reduced fallback phase set: train + prefill/decode/batched-decode
        # throughput only — the headline serving trajectory without the
        # chip-specific contention/sweep phases.
        decode_tps = bench_decode_throughput(arch, params, mapper,
                                             block=block, tokens=8)
        emit(decode_tokens_per_sec=round(decode_tps, 1))
        batched_tps, batched_n = bench_batched_decode(arch, params,
                                                      block=block, tokens=4,
                                                      batch=3)
        emit(batched_decode_tokens_per_sec=round(batched_tps, 1),
             batched_decode_batch=batched_n)
        print(json.dumps({
            "metric": "gpt2-124M train tokens/sec/chip",
            "unit": "tokens/sec/chip",
            **_partial,
        }))
        return
    busy_kw = dict(trials=3, train_batch=2, train_steps=2) if smoke else {}
    # Policy off first (PENROZ_DECODE_PRIORITY_MS=0 disables the trainer's
    # between-epoch yield), then on: the delta quantifies decode-priority
    # dispatch on-chip rather than asserting it.
    prev_priority = os.environ.get("PENROZ_DECODE_PRIORITY_MS")
    os.environ["PENROZ_DECODE_PRIORITY_MS"] = "0"
    try:
        ttft_nopriority = bench_ttft_under_train(arch, params, mapper,
                                                 block=block, **busy_kw)
    finally:
        if prev_priority is None:
            os.environ.pop("PENROZ_DECODE_PRIORITY_MS", None)
        else:
            os.environ["PENROZ_DECODE_PRIORITY_MS"] = prev_priority
    if ttft_nopriority is not None:
        emit(ttft_under_train_nopriority_ms_p50=round(ttft_nopriority, 2))
    ttft_busy = bench_ttft_under_train(arch, params, mapper, block=block,
                                       **busy_kw)
    if ttft_busy is not None:
        emit(ttft_under_train_ms_p50=round(ttft_busy, 2))

    decode_tps = bench_decode_throughput(arch, params, mapper, block=block,
                                         tokens=8 if smoke else 96)
    emit(decode_tokens_per_sec=round(decode_tps, 1))
    paged_tps, paged_assigned = bench_paged_generate(
        arch, params, block=block, tokens=8 if smoke else 64)
    emit(paged_decode_tokens_per_sec=round(paged_tps, 1),
         paged_assigned_mb=round(paged_assigned / 2 ** 20, 2),
         paged_vs_contiguous=round(paged_tps / decode_tps, 3))
    batched_tps, batched_n = bench_batched_decode(
        arch, params, block=block, tokens=4 if smoke else 64,
        batch=3 if smoke else 8)
    emit(batched_decode_tokens_per_sec=round(batched_tps, 1),
         batched_decode_batch=batched_n)

    # MoE before long-context: the amortized dispatch ratio is a judged
    # deliverable, while the long-ctx tuning sweep is open-ended — if the
    # pool dies mid-sweep the priority metrics must already be in the
    # partial.
    moe = bench_moe_dispatch(**(dict(d=64, experts=4, top_k=2, depth=2,
                                     batch=2, block=64, timed=1)
                                if smoke else {}))
    if moe:
        emit(moe_dense_tokens_per_sec=round(moe[0], 1),
             moe_capacity_tokens_per_sec=round(moe[1], 1),
             moe_speedup=round(moe[1] / moe[0], 3))
    long_ctx = bench_long_context(**(dict(depth=2, d_model=64, block=512,
                                          timed=1, heads=4)
                                     if smoke else {}))
    if long_ctx:
        emit(long_ctx_tokens_per_sec=round(long_ctx[0], 1),
             long_ctx_mfu=round(long_ctx[1], 4), long_ctx_block=long_ctx[2],
             long_ctx_cfg=long_ctx[3])

    print(json.dumps({
        "metric": "gpt2-124M train tokens/sec/chip",
        "unit": "tokens/sec/chip",
        **_partial,
    }))


if __name__ == "__main__":
    main()
