#!/bin/bash
# Chaos matrix: every registered fault site (utils/faults.py) x scheduler
# mode {unified, phased}, each combo driven through the overload chaos
# bench (scripts/bench_serving.py --chaos, paged KV).  `unified` is the
# ragged one-dispatch mixed tick (PENROZ_RAGGED_ATTENTION=1, the default);
# `phased` is the legacy prefill/decode-phase scheduler the =0 escape
# hatch restores.  A combo passes iff the bench's `ok` gate holds: no
# status outside 200/429/503/504 (the armed crash's own 500s excepted)
# and the post-fault solo replay of every prompt is greedy
# token-identical to its pre-chaos baseline.  Any failed combo fails the
# script (exit 1) with the offending JSON line printed.
#
# CHAOS_FAST=1 runs a single representative combo (qos.preempt x unified
# — the newest recovery path, on the ragged mixed-dispatch engine) so a
# tier-1 test can afford the sweep; the full matrix is the pre-release /
# soak entry point.
#
# CHAOS_RESTART=1 runs ONLY the crash-durability drills (PR 18) and
# exits: real-subprocess SIGKILL both mid-hibernation-demotion and
# post-demotion, each followed by a restart whose journal replay must
# rebuild a consistent registry and whose next turn must hit greedy
# token parity — plus the --restart bench's journal/reconnect gates.
# Both run under PENROZ_MEMLEDGER_STRICT=1.
#
# Env passthrough: PENROZ_BENCH_SERVING_PLATFORM, PENROZ_BENCH_* scale
# knobs.  CHAOS_SITES / CHAOS_MODES / CHAOS_REPLICAS override the swept
# sets (space-separated).  CHAOS_REPLICAS > 1 runs the combo through the
# replica router (serve/router.py): a fault that crashes one replica must
# leave its siblings' in-flight rows untouched, and the post-fault solo
# replay parity gate holds for the whole group.
set -u
cd "$(dirname "$0")/.."

if [ "${CHAOS_RESTART:-0}" = "1" ]; then
  # SIGKILL drills: phase-1 process hibernates a session and is killed —
  # once the moment the first turn completes (demotion still in flight),
  # once after the disk spill settled — and the phase-2 process must
  # replay the journal to a consistent registry and resume at greedy
  # parity.  The pytest entry points own the subprocess plumbing.
  echo "=== chaos restart: SIGKILL mid-demotion + post-demotion ===" >&2
  if ! PENROZ_MEMLEDGER_STRICT=1 timeout 900 env JAX_PLATFORMS=cpu \
      python -m pytest tests/test_journal.py -q -k sigkill \
      -p no:cacheprovider; then
    echo "chaos restart: FAILED (SIGKILL drills)" >&2
    exit 1
  fi
  echo "=== chaos restart: --restart bench (replay + reconnect gates) ===" >&2
  out=$(PENROZ_MEMLEDGER_STRICT=1 timeout 900 \
          python scripts/bench_serving.py --restart)
  rc=$?
  echo "$out"
  if [ "$rc" -ne 0 ] || ! printf '%s' "$out" | python -c \
      'import json,sys; r=json.loads(sys.stdin.read().strip().splitlines()[-1]); sys.exit(0 if r.get("ok") else 1)'; then
    echo "chaos restart: FAILED (--restart bench)" >&2
    exit 1
  fi
  echo "chaos restart: OK" >&2
  exit 0
fi

SITES="${CHAOS_SITES:-decode.step decode.prefill_chunk decode.verify ckpt.write data.download lora.load qos.preempt}"
MODES="${CHAOS_MODES:-unified phased}"
REPLICAS="${CHAOS_REPLICAS:-1}"
if [ "${CHAOS_FAST:-0}" = "1" ]; then
  SITES="qos.preempt"
  MODES="unified"
fi

fail=0
ran=0
for site in $SITES; do
  for mode in $MODES; do
    for nrep in $REPLICAS; do
      ran=$((ran + 1))
      ragged=1
      [ "$mode" = "phased" ] && ragged=0
      echo "=== chaos: site=$site mode=$mode replicas=$nrep ===" >&2
      # Strict memory ledger: every retirement/preemption/crash recovery in
      # the sweep re-proves the page-ownership invariant (serve/memledger.py)
      # — a leaked page raises in the engine worker and fails the combo.
      out=$(PENROZ_BENCH_CHAOS_SITE="$site" PENROZ_RAGGED_ATTENTION="$ragged" \
              PENROZ_MEMLEDGER_STRICT=1 PENROZ_SCHED_REPLICAS="$nrep" \
              timeout 900 python scripts/bench_serving.py --chaos)
      rc=$?
      echo "$out"
      if [ "$rc" -ne 0 ]; then
        echo "FAIL site=$site mode=$mode replicas=$nrep rc=$rc" >&2
        fail=1
        continue
      fi
      if ! printf '%s' "$out" | python -c \
          'import json,sys; r=json.loads(sys.stdin.read().strip().splitlines()[-1]); sys.exit(0 if r.get("ok") else 1)'; then
        echo "FAIL site=$site mode=$mode replicas=$nrep: disallowed statuses or parity break" >&2
        fail=1
      fi
    done
  done
done

# Disaggregated-prefill hand-off sweep (PR 15): disagg.handoff fires once
# per export and once per import, so ordinal 3 crashes the second
# hand-off mid-export and ordinal 4 crashes it mid-import.  Both must
# fall back to monolithic prefill with greedy parity, leak no transit
# pages (strict ledger) and no staged page blobs.  Skipped under
# CHAOS_FAST (the tier-1 representative combo stays single-replica).
if [ "${CHAOS_FAST:-0}" != "1" ]; then
  for at in ${CHAOS_DISAGG_ATS:-3 4}; do
    ran=$((ran + 1))
    echo "=== chaos: site=disagg.handoff at=$at replicas=2 disagg=1 ===" >&2
    out=$(PENROZ_BENCH_CHAOS_SITE=disagg.handoff PENROZ_BENCH_CHAOS_AT="$at" \
            PENROZ_DISAGG_PREFILL=1 PENROZ_SCHED_REPLICAS=2 \
            PENROZ_RAGGED_ATTENTION=1 PENROZ_MEMLEDGER_STRICT=1 \
            timeout 900 python scripts/bench_serving.py --chaos)
    rc=$?
    echo "$out"
    if [ "$rc" -ne 0 ]; then
      echo "FAIL site=disagg.handoff at=$at rc=$rc" >&2
      fail=1
      continue
    fi
    if ! printf '%s' "$out" | python -c \
        'import json,sys; r=json.loads(sys.stdin.read().strip().splitlines()[-1]); sys.exit(0 if r.get("ok") else 1)'; then
      echo "FAIL site=disagg.handoff at=$at: disallowed statuses or parity break" >&2
      fail=1
    fi
  done

  # disagg.d2d (PR 16): the device-to-device transport specifically —
  # ordinal 3 crashes an exporter-side device hand-over, ordinal 4 an
  # importer-side re-shard+scatter.  Both must fall back to the
  # host-staged blob for THAT hand-off (not monolithic prefill) with
  # greedy parity and a clean strict ledger.
  for at in ${CHAOS_D2D_ATS:-3 4}; do
    ran=$((ran + 1))
    echo "=== chaos: site=disagg.d2d at=$at replicas=2 disagg=1 ===" >&2
    out=$(PENROZ_BENCH_CHAOS_SITE=disagg.d2d PENROZ_BENCH_CHAOS_AT="$at" \
            PENROZ_DISAGG_PREFILL=1 PENROZ_SCHED_REPLICAS=2 \
            PENROZ_RAGGED_ATTENTION=1 PENROZ_MEMLEDGER_STRICT=1 \
            timeout 900 python scripts/bench_serving.py --chaos)
    rc=$?
    echo "$out"
    if [ "$rc" -ne 0 ]; then
      echo "FAIL site=disagg.d2d at=$at rc=$rc" >&2
      fail=1
      continue
    fi
    if ! printf '%s' "$out" | python -c \
        'import json,sys; r=json.loads(sys.stdin.read().strip().splitlines()[-1]); sys.exit(0 if r.get("ok") else 1)'; then
      echo "FAIL site=disagg.d2d at=$at: disallowed statuses or parity break" >&2
      fail=1
    fi
  done

  # KV tiering / session hibernation (PR 17): tier.demote crashes the
  # background spill of a freshly hibernated session's pages (worker-loop
  # tail), tier.promote crashes a hibernated wake mid-import.  The bench
  # attaches session ids and replays full histories so both sites really
  # execute while armed; each crash must recover through the standard
  # engine reset with no leaked hibernating pages (strict ledger audits
  # the demote seam and crash recovery) and greedy parity on the solo
  # replay — a hibernated wake after the crash recomputes or re-imports,
  # never serves wrong tokens.
  for tsite in ${CHAOS_TIER_SITES:-tier.demote tier.promote}; do
    ran=$((ran + 1))
    echo "=== chaos: site=$tsite sessions=1 ===" >&2
    out=$(PENROZ_BENCH_CHAOS_SITE="$tsite" \
            PENROZ_RAGGED_ATTENTION=1 PENROZ_MEMLEDGER_STRICT=1 \
            timeout 900 python scripts/bench_serving.py --chaos)
    rc=$?
    echo "$out"
    if [ "$rc" -ne 0 ]; then
      echo "FAIL site=$tsite rc=$rc" >&2
      fail=1
      continue
    fi
    if ! printf '%s' "$out" | python -c \
        'import json,sys; r=json.loads(sys.stdin.read().strip().splitlines()[-1]); sys.exit(0 if r.get("ok") and r.get("sessions_hibernated", 0) > 0 else 1)'; then
      echo "FAIL site=$tsite: disallowed statuses, parity break, or no hibernation" >&2
      fail=1
    fi
  done

  # Crash-durable serving (PR 18): the three durability fault sites, all
  # under the strict memory ledger.
  #
  # - journal.append: the Nth write-ahead append fails (disk error) —
  #   MUST be contained (append returns False, request succeeds); gate
  #   on append_errors > 0 proving the site really fired.
  # - journal.replay: the startup replay crashes (at=1: the only call) —
  #   the armed restart must come up with an empty-but-consistent
  #   registry AND leave the disk blobs alone, so the follow-up clean
  #   restart recovers every session (sessions_recovered gate inside the
  #   bench ok) at greedy parity.
  # - stream.resume: the Nth from_seq reattach crashes (500) — the retry
  #   must deliver the missed tokens exactly once (stream_exactly_once
  #   folded into the bench ok).
  for jsite in ${CHAOS_DURABILITY_SITES:-journal.append journal.replay stream.resume}; do
    ran=$((ran + 1))
    at=3
    [ "$jsite" = "journal.replay" ] && at=1
    echo "=== chaos: site=$jsite at=$at durability=1 ===" >&2
    out=$(PENROZ_BENCH_CHAOS_SITE="$jsite" PENROZ_BENCH_CHAOS_AT="$at" \
            PENROZ_RAGGED_ATTENTION=1 PENROZ_MEMLEDGER_STRICT=1 \
            timeout 900 python scripts/bench_serving.py --chaos)
    rc=$?
    echo "$out"
    if [ "$rc" -ne 0 ]; then
      echo "FAIL site=$jsite rc=$rc" >&2
      fail=1
      continue
    fi
    case "$jsite" in
      journal.append) gate='r.get("ok") and r.get("journal", {}).get("append_errors", 0) > 0' ;;
      journal.replay) gate='r.get("ok") and r.get("replay_errors_armed", 0) > 0 and r.get("sessions_recovered", 0) > 0' ;;
      *)              gate='r.get("ok") and r.get("stream_resume_faults", 0) > 0 and r.get("stream_stats", {}).get("resumes", 0) > 0' ;;
    esac
    if ! printf '%s' "$out" | python -c \
        "import json,sys; r=json.loads(sys.stdin.read().strip().splitlines()[-1]); sys.exit(0 if ($gate) else 1)"; then
      echo "FAIL site=$jsite: disallowed statuses, parity break, or site never fired" >&2
      fail=1
    fi
  done

  # Pipeline-parallel serving (PENROZ_SERVE_PIPE_STAGES=2): the two
  # stage-schedule fault sites, both on the ragged unified engine with
  # the strict ledger re-proving the per-stage pool partition.
  #
  # - pipe.handoff crashes a stage-to-stage activation transfer
  #   mid-flight — CONTAINED: the hand-off re-stages through the host
  #   (gate on pipe_handoff_host_fallbacks > 0 proving the site really
  #   fired) and the solo replay stays greedy token-identical.
  # - pipe.stage_crash raises at the top of a stage-unit dispatch —
  #   propagates like any stage failure: the worker's crash handler must
  #   reallocate the WHOLE group (gate on engine_resets > 0), the strict
  #   audit must stay clean, and parity must hold after recovery.
  for psite in ${CHAOS_PIPE_SITES:-pipe.handoff pipe.stage_crash}; do
    ran=$((ran + 1))
    echo "=== chaos: site=$psite stages=2 ===" >&2
    out=$(PENROZ_BENCH_CHAOS_SITE="$psite" PENROZ_SERVE_PIPE_STAGES=2 \
            PENROZ_RAGGED_ATTENTION=1 PENROZ_MEMLEDGER_STRICT=1 \
            timeout 900 python scripts/bench_serving.py --chaos)
    rc=$?
    echo "$out"
    if [ "$rc" -ne 0 ]; then
      echo "FAIL site=$psite rc=$rc" >&2
      fail=1
      continue
    fi
    case "$psite" in
      pipe.handoff) gate='r.get("ok") and r.get("pipe_handoff_host_fallbacks", 0) > 0' ;;
      *)            gate='r.get("ok") and r.get("engine_resets", 0) > 0' ;;
    esac
    if ! printf '%s' "$out" | python -c \
        "import json,sys; r=json.loads(sys.stdin.read().strip().splitlines()[-1]); sys.exit(0 if ($gate) else 1)"; then
      echo "FAIL site=$psite: disallowed statuses, parity break, or site never fired" >&2
      fail=1
    fi
  done

  # disagg.rebalance (PR 16): crash the first elastic role-flip attempt
  # (the bench arms elastic together with the fault, so flip #1 runs
  # armed).  The crash must recover with the role registry consistent
  # and the flip applied on retry — the bench's ok gate plus role
  # evidence in its disagg_role_changes field.
  ran=$((ran + 1))
  echo "=== chaos: site=disagg.rebalance at=1 replicas=3 elastic=1 ===" >&2
  out=$(PENROZ_BENCH_CHAOS_SITE=disagg.rebalance PENROZ_BENCH_CHAOS_AT=1 \
          PENROZ_RAGGED_ATTENTION=1 PENROZ_MEMLEDGER_STRICT=1 \
          timeout 900 python scripts/bench_serving.py --chaos)
  rc=$?
  echo "$out"
  if [ "$rc" -ne 0 ]; then
    echo "FAIL site=disagg.rebalance rc=$rc" >&2
    fail=1
  elif ! printf '%s' "$out" | python -c \
      'import json,sys; r=json.loads(sys.stdin.read().strip().splitlines()[-1]); sys.exit(0 if r.get("ok") and r.get("disagg_role_changes", 0) > 0 else 1)'; then
    echo "FAIL site=disagg.rebalance: disallowed statuses, parity break, or no role flip" >&2
    fail=1
  fi

  # SSM recurrent-state sites (PR 18).  The bench serves a HYBRID
  # (attention + ssm) model for ssm.* sites, so every combo carries real
  # recurrent row state (gated on ssm_state_bytes > 0).
  # - ssm.scan raises inside the recurrent prefill/decode scan update —
  #   a mid-dispatch crash: the engine must reset (engine_resets > 0) and
  #   re-admitted rows must replay greedy token-identical, proving the
  #   recurrent planes were rebuilt, not resumed from poisoned state.
  # - ssm.handoff raises mid-export of a recurrent row blob on the disagg
  #   hand-off path (the bench pins the host transport so the d2d path
  #   can't absorb the fault by re-staging): the prefill replica must fall
  #   back to monolithic serving with parity, the failure counted in
  #   disagg_handoff_failures, and the strict ledger clean on both sides.
  for ssite in ${CHAOS_SSM_SITES:-ssm.scan ssm.handoff}; do
    ran=$((ran + 1))
    echo "=== chaos: site=$ssite hybrid=1 ===" >&2
    out=$(PENROZ_BENCH_CHAOS_SITE="$ssite" \
            PENROZ_RAGGED_ATTENTION=1 PENROZ_MEMLEDGER_STRICT=1 \
            timeout 900 python scripts/bench_serving.py --chaos)
    rc=$?
    echo "$out"
    if [ "$rc" -ne 0 ]; then
      echo "FAIL site=$ssite rc=$rc" >&2
      fail=1
      continue
    fi
    case "$ssite" in
      ssm.handoff) gate='r.get("ok") and r.get("disagg_handoff_failures", 0) > 0 and r.get("ssm_state_bytes", 0) > 0' ;;
      *)           gate='r.get("ok") and r.get("engine_resets", 0) > 0 and r.get("ssm_state_bytes", 0) > 0' ;;
    esac
    if ! printf '%s' "$out" | python -c \
        "import json,sys; r=json.loads(sys.stdin.read().strip().splitlines()[-1]); sys.exit(0 if ($gate) else 1)"; then
      echo "FAIL site=$ssite: disallowed statuses, parity break, or site never fired" >&2
      fail=1
    fi
  done
fi

if [ "$fail" -ne 0 ]; then
  echo "chaos matrix: FAILED (of $ran combos)" >&2
  exit 1
fi
echo "chaos matrix: OK ($ran combos)" >&2
