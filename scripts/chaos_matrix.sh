#!/bin/bash
# Chaos matrix: every registered fault site (utils/faults.py) x compiled
# superstep {1, 8}, each combo driven through the overload chaos bench
# (scripts/bench_serving.py --chaos).  A combo passes iff the bench's `ok`
# gate holds: no status outside 200/429/503/504 (the armed crash's own
# 500s excepted) and the post-fault solo replay of every prompt is greedy
# token-identical to its pre-chaos baseline.  Any failed combo fails the
# script (exit 1) with the offending JSON line printed.
#
# CHAOS_FAST=1 runs a single representative combo (qos.preempt x
# superstep 8 — the newest recovery path, on the fused-dispatch engine) so
# a tier-1 test can afford the sweep; the full matrix is the pre-release /
# soak entry point.
#
# Env passthrough: PENROZ_BENCH_SERVING_PLATFORM, PENROZ_BENCH_* scale
# knobs.  CHAOS_SITES / CHAOS_SUPERSTEPS override the swept sets
# (space-separated).
set -u
cd "$(dirname "$0")/.."

SITES="${CHAOS_SITES:-decode.step decode.prefill_chunk decode.verify ckpt.write data.download lora.load qos.preempt}"
SUPERSTEPS="${CHAOS_SUPERSTEPS:-1 8}"
if [ "${CHAOS_FAST:-0}" = "1" ]; then
  SITES="qos.preempt"
  SUPERSTEPS="8"
fi

fail=0
ran=0
for site in $SITES; do
  for ss in $SUPERSTEPS; do
    ran=$((ran + 1))
    echo "=== chaos: site=$site superstep=$ss ===" >&2
    out=$(PENROZ_BENCH_CHAOS_SITE="$site" PENROZ_SCHED_SUPERSTEP="$ss" \
            timeout 900 python scripts/bench_serving.py --chaos)
    rc=$?
    echo "$out"
    if [ "$rc" -ne 0 ]; then
      echo "FAIL site=$site superstep=$ss rc=$rc" >&2
      fail=1
      continue
    fi
    if ! printf '%s' "$out" | python -c \
        'import json,sys; r=json.loads(sys.stdin.read().strip().splitlines()[-1]); sys.exit(0 if r.get("ok") else 1)'; then
      echo "FAIL site=$site superstep=$ss: disallowed statuses or parity break" >&2
      fail=1
    fi
  done
done

if [ "$fail" -ne 0 ]; then
  echo "chaos matrix: FAILED (of $ran combos)" >&2
  exit 1
fi
echo "chaos matrix: OK ($ran combos)" >&2
