"""Serving benchmark: N concurrent /generate/ requests, continuous-batching
scheduler ON vs OFF, against the real aiohttp app in-process.

Measures the acceptance shape of the scheduler directly: with the scheduler
enabled, N concurrent greedy requests share one batch-N decode step per
token, so their wall-clock approaches one request's — while the legacy path
runs N independent batch-1 decode loops.  Greedy outputs are asserted
token-identical between the serial-off baseline and every other phase
(``parity_ok``), so the speedup is never bought with wrong tokens.

Prints ONE JSON line, e.g.::

  {"concurrency": 8, "max_new_tokens": 48,
   "scheduler_off": {"serial_s": ..., "concurrent_s": ...},
   "scheduler_on":  {"serial_s": ..., "concurrent_s": ...},
   "concurrent_speedup_on_vs_off": 3.1,
   "concurrent_on_vs_serial_off": 4.9,
   "parity_ok": true, "serving_stats": {...}}

CPU by default (``PENROZ_BENCH_SERVING_PLATFORM`` overrides); run from the
repo root: ``python scripts/bench_serving.py [concurrency] [max_new]``.

``--overload`` switches to the fault-tolerance workload: offered load >
capacity against a deliberately small engine (``PENROZ_BENCH_OVER_ROWS``
rows, ``PENROZ_BENCH_OVER_QUEUE`` queue slots, ``PENROZ_BENCH_OVER_N``
concurrent requests fired in waves), reporting the shed rate (429s),
goodput (completed requests/sec), goodput latency p50/p99, and greedy
parity of every completed response against its solo baseline — load
shedding must never corrupt an admitted request (zero non-(200|429)
statuses asserted by tests/test_bench_serving.py).

``--shared-prefix`` switches to the chunked-prefill + radix prefix-cache
workload: N sequential streaming requests sharing one long prompt prefix
(distinct short suffixes), measured with the prefix cache OFF then ON
(``PENROZ_PREFIX_CACHE``), reporting TTFT p50/p99 and ITL p99 per phase,
the cache hit rate, and the TTFT speedup.  Greedy parity is asserted
between phases.  JSON goes to stdout and (``PENROZ_BENCH_JSON_OUT``) to a
file for ``bench_watch.sh``-style artifact capture.  Scale knobs (env):
``PENROZ_BENCH_SERVING_BLOCK/_D/_DEPTH``, ``PENROZ_BENCH_PREFIX_LEN``,
``PENROZ_BENCH_SUFFIX_LEN``, ``PENROZ_BENCH_REQUESTS``,
``PENROZ_BENCH_PREFIX_PAGE`` (KV page size), ``PENROZ_BENCH_CHUNK``
(prefill chunk).

``--multi-adapter`` switches to the multi-tenant LoRA workload: N tenants
(distinct random adapters + the base model) stream requests; phase
``serial_per_adapter`` runs one tenant's batched group at a time (the
best a per-adapter-engine deployment can do) and phase ``mixed`` fires
every tenant concurrently so rows with DIFFERENT adapters share one
decode step via the stacked adapter pack (models/lora.py).  Reports wall
time + ITL p50/p99 per phase, the mixed-vs-serial wall speedup, greedy
per-request parity between phases, and the ``lora_*`` serving stats.
Scale knobs: ``PENROZ_BENCH_LORA_ADAPTERS``, ``PENROZ_BENCH_LORA_RANK``,
``PENROZ_BENCH_LORA_PROMPT``, plus the shared ``PENROZ_BENCH_SERVING_*``
/ ``PENROZ_BENCH_REQUESTS`` / ``PENROZ_BENCH_MAX_NEW`` set.

``--speculative`` switches to the speculative-decoding workload:
sequential streaming requests over repetitive-text prompts (short token
motifs repeated — the shape prompt lookup exists for), measured with
``PENROZ_SPEC_DECODE`` OFF then ON, reporting ITL p50/p99 and — the
headline — **tokens per decode step** per phase plus the draft accept
rate.  Sequential single-row traffic pins the off-phase at exactly 1.0
token/step, so the on/off ratio isolates what speculation buys.  Greedy
parity is asserted between phases (the verify step must never trade
correctness for speed).  Every mode's JSON capture now carries the
aggregate ``tokens_per_decode_step`` + ``spec_accept_rate`` fields via
``serving_stats``.  Scale knobs: ``PENROZ_BENCH_SPEC_K``,
``PENROZ_BENCH_SPEC_NGRAM``, ``PENROZ_BENCH_SPEC_PROMPT``,
``PENROZ_BENCH_SPEC_VOCAB``, plus the shared ``PENROZ_BENCH_SERVING_*`` /
``PENROZ_BENCH_REQUESTS`` / ``PENROZ_BENCH_MAX_NEW`` set.

``--mixed-slo`` switches to the SLO-tiered QoS workload (PR 8): a batch
flood saturates a deliberately small engine while interactive probes
stream through it, measured classless (``fifo`` — the pre-QoS single
sub-queue) then with SLO classes + preemption (``qos``).  Headline
fields: ``slo_ok_qos`` (interactive p99 TTFT under QoS within the
``PENROZ_BENCH_QOS_SLO_MS`` budget, default 50 ms, floored at 2× the
unloaded p99) and ``slo_exceeded_fifo`` (FIFO blows that budget).  A final
``quota`` phase pins per-tenant shedding: only the over-budget tenant
429s, the victim completes with greedy parity.  Scale knobs:
``PENROZ_BENCH_QOS_ROWS/_FLOOD/_PROBES/_PROBE_NEW/_RATE`` plus the
shared ``PENROZ_BENCH_SERVING_BLOCK`` / ``PENROZ_BENCH_MAX_NEW``.

``--ragged`` switches to the unified ragged-attention workload (PR 9):
short decode streams run while long prompts chunk-prefill through the
same engine, measured contiguous-legacy (``PAGED_KV_CACHE=0`` — the
phased scheduler) then paged-unified (``=1`` — one dispatch over the
mixed batch).  Headlines: mixed ITL p50/p99 of the decode streams,
tokens per dispatch (the paged path must be ≥ contiguous on the same
offered load — ``paged_ge_contiguous``), greedy parity, and the tick
timeline's ``mixed_fused_superstep_max`` (a single dispatch carrying
prefill chunks AND n>1 fused decode steps — the regime the PR 7
fallbacks forbade).  Scale knobs: ``PENROZ_BENCH_RAGGED_STREAMS/
_PREFILLS/_PROMPT/_LONG/_PREFILL_NEW`` plus the shared set.

``--disagg`` switches to the disaggregated-prefill workload (PR 15):
interactive decode streams share a 2-replica group with long prompts,
measured co-located (``PENROZ_DISAGG_PREFILL=0`` — every replica admits,
prefills and decodes) then disaggregated (``=1`` — replica 0 runs
prefill to completion and exports finished KV pages, replica 1 imports
and decodes, never executing a prefill chunk).  Headlines: decode ITL
p50/p99 of the interactive streams (the latency long-prompt chunks
pollute when they share the decode engine's tick loop), long-prompt
TTFT (now including the hand-off), hand-off latency p50/p99 +
export/import/failure counters, and tokens per dispatch on decode-role
replicas.  Greedy parity is asserted between phases.  Scale knobs:
``PENROZ_BENCH_DISAGG_STREAMS/_PREFILLS/_PROMPT/_LONG/_PREFILL_NEW``
plus the shared ``PENROZ_BENCH_SERVING_*`` / ``PENROZ_BENCH_MAX_NEW`` /
``PENROZ_BENCH_CHUNK`` set.

``--memory`` switches to the capacity-ledger workload
(serve/memledger.py): sequential streaming ITLs with the ledger off
(``PENROZ_MEMLEDGER=0``) vs on, greedy parity asserted and the delta
recorded (the ledger derives ownership at read time, so decode must not
pay for it) — then two tenants decode concurrently while ``GET
/memory/`` is polled: both must show nonzero per-tenant page counts and
every poll must see the page states sum to pool capacity.  Runs with
``PENROZ_MEMLEDGER_STRICT=1`` (a leaked page fails the bench) and gates
``ok`` on parity + invariant + attribution + zero lifetime
drop/underflow/audit counters.  Scale knobs: ``PENROZ_BENCH_MEM_PAGE``,
``PENROZ_BENCH_MEM_PROMPT``, plus the shared ``PENROZ_BENCH_SERVING_*``
/ ``PENROZ_BENCH_REQUESTS`` / ``PENROZ_BENCH_MAX_NEW`` set.

``--chaos`` arms ONE fault site (``PENROZ_BENCH_CHAOS_SITE``, default
``qos.preempt``; Nth trigger via ``PENROZ_BENCH_CHAOS_AT``) and drives
mixed-priority overload waves through it — the building block
``scripts/chaos_matrix.sh`` sweeps across every registered site ×
superstep {1, 8}.  Reports the status histogram (anything outside
200/429/503/504 — plus the armed crash's own 500s — lands in
``disallowed``), crash/preemption counts, and post-fault greedy parity
(``parity_ok``); ``ok`` is the single gate the matrix script checks.

Observability (PR 6): every scenario scrapes ``GET /metrics`` before and
after its run and embeds the counter/histogram deltas as
``metrics_delta`` in the JSON capture — committed bench captures double
as a metrics regression record.  The default mode also runs a
``trace_overhead`` phase: sequential streaming ITLs with per-request
tracing sampled out (``PENROZ_TRACE_SAMPLE=0``) vs full (``=1``), greedy
parity asserted, delta recorded.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS",
                      os.environ.get("PENROZ_BENCH_SERVING_PLATFORM", "cpu"))

import asyncio  # noqa: E402
import json  # noqa: E402
import tempfile  # noqa: E402
import time  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_metrics(text: str) -> dict:
    """Flat ``{series: value}`` map of a Prometheus text exposition —
    ``penroz_requests_total{outcome="completed"} 12`` becomes one entry."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


async def _scrape_metrics(client) -> dict:
    resp = await client.get("/metrics")
    assert resp.status == 200, await resp.text()
    return _parse_metrics(await resp.text())


def _metrics_delta(before: dict, after: dict) -> dict:
    """What this scenario did to the monotonic series (counters and
    histogram sums/counts; gauges are instantaneous and excluded):
    embedded in every bench JSON capture so the bench history doubles as
    a metrics regression record — a scenario that stops moving
    ``penroz_spec_accepted_tokens_total`` shows up in the diff of its
    committed captures, not just in a live Prometheus."""
    delta = {}
    for key, value in after.items():
        base = key.split("{", 1)[0]
        if base.endswith("_bucket") or not (
                base.endswith("_total") or base.endswith("_sum")
                or base.endswith("_count")):
            continue
        d = value - before.get(key, 0.0)
        if d:
            delta[key] = round(d, 3)
    return delta


def _toy_gpt(d=256, heads=8, vocab=512, block=256, depth=4):
    """Small-but-real GPT stack (attention + KV cache on the hot path) —
    sized so a forward's compute dominates per-dispatch overhead on CPU,
    the regime the scheduler exists for (a micro-model measures dispatch
    floors, not batching)."""
    return ([{"summation": [
                {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
                 "normal": {"mean": 0.0, "std": 0.02}},
                {"position": {"num_embeddings": block, "embedding_dim": d},
                 "normal": {"mean": 0.0, "std": 0.02}}]}]
            + [{"residual": [
                {"sequential": [
                    {"layernorm": {"normalized_shape": d}},
                    {"linear": {"in_features": d, "out_features": 3 * d},
                     "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                    {"attention": {"num_heads": heads, "dropout": 0.0}},
                    {"linear": {"in_features": d, "out_features": d}}]},
                {"sequential": [
                    {"layernorm": {"normalized_shape": d}},
                    {"linear": {"in_features": d, "out_features": 4 * d}},
                    {"gelu": {}},
                    {"linear": {"in_features": 4 * d, "out_features": d}}]},
               ]} for _ in range(depth)]
            + [{"layernorm": {"normalized_shape": d}},
               {"linear": {"in_features": d, "out_features": vocab,
                           "bias": False}},
               {"softmaxlast": {"dim": -1}}])


def _toy_hybrid(d=256, heads=8, vocab=512, block=256, depth=4,
                ssm_every=2):
    """Hybrid twin of :func:`_toy_gpt`: every ``ssm_every``-th block is a
    gated linear-attention (O(1) recurrent state) block instead of
    attention+KV (models/presets.py::hybrid_custom)."""
    from penroz_tpu.models import presets
    return presets.hybrid_custom(d=d, heads=heads, depth=depth, vocab=vocab,
                                 block=block, dropout=0.0,
                                 ssm_every=ssm_every)


async def _bench(concurrency: int, max_new: int, block: int) -> dict:
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(0)
    prompts = [[int(t) for t in rng.integers(1, 255, 8 + (i % 5))]
               for i in range(concurrency)]

    async def generate(prompt):
        resp = await client.post("/generate/", json={
            "model_id": "bench-serving", "input": [prompt],
            "block_size": block, "max_new_tokens": max_new,
            "temperature": 0.0})
        body = await resp.json()
        assert resp.status == 200, body
        return body["tokens"]

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-serving", "layers": _toy_gpt(block=block),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()

        results: dict = {"concurrency": concurrency,
                         "max_new_tokens": max_new, "block_size": block}
        metrics_before = await _scrape_metrics(client)
        baselines = None
        parity_ok = True
        for mode in ("off", "on"):
            os.environ[decode_scheduler.ENABLE_ENV] = \
                "1" if mode == "on" else "0"
            # Warm every prompt shape per mode: prefill programs retrace per
            # prompt length, and the timed rounds must compare steady-state
            # serving, not who pays the compiles.
            for p in prompts:
                await generate(p)
            t0 = time.perf_counter()
            serial = [await generate(p) for p in prompts]
            serial_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            concurrent = await asyncio.gather(*[generate(p)
                                                for p in prompts])
            concurrent_s = time.perf_counter() - t0
            if baselines is None:
                baselines = serial
            parity_ok = parity_ok and serial == baselines \
                and list(concurrent) == baselines
            total_tokens = concurrency * max_new
            results[f"scheduler_{mode}"] = {
                "serial_s": round(serial_s, 3),
                "concurrent_s": round(concurrent_s, 3),
                "concurrent_tokens_per_sec": round(
                    total_tokens / concurrent_s, 1),
            }
        off, on = results["scheduler_off"], results["scheduler_on"]
        results["concurrent_speedup_on_vs_off"] = round(
            off["concurrent_s"] / on["concurrent_s"], 3)
        results["concurrent_on_vs_serial_off"] = round(
            off["serial_s"] / on["concurrent_s"], 3)
        results["parity_ok"] = parity_ok
        results["trace_overhead"] = await _bench_trace_overhead(
            client, prompts, max_new, block)
        resp = await client.get("/serving_stats/")
        stats = await resp.json()
        stats.pop("engines", None)
        stats.pop("tick_timeline", None)  # per-tick samples, not a summary
        results["serving_stats"] = stats
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        os.environ.pop(decode_scheduler.ENABLE_ENV, None)
        os.environ.pop("PENROZ_TRACE_SAMPLE", None)


async def _bench_trace_overhead(client, prompts, max_new, block) -> dict:
    """Per-request tracing is host-side span bookkeeping; this phase pins
    that it stays invisible next to a decode dispatch: sequential
    streaming ITLs through the scheduler with PENROZ_TRACE_SAMPLE=0 vs 1,
    greedy parity asserted, the delta recorded in the JSON capture (the
    acceptance bar is 'within noise', so the capture records the evidence,
    not a hard threshold that would flake on shared CI boxes)."""
    from penroz_tpu.serve import decode_scheduler
    os.environ[decode_scheduler.ENABLE_ENV] = "1"
    out: dict = {}
    seqs = {}
    sample = prompts[:4]
    for phase in ("off", "on"):
        os.environ["PENROZ_TRACE_SAMPLE"] = "0" if phase == "off" else "1"
        itls, toks_all = [], []
        for p in sample:
            toks, _, gaps = await _stream_one(client, {
                "model_id": "bench-serving", "input": [p],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0})
            itls.extend(gaps)
            toks_all.append(toks)
        seqs[phase] = toks_all
        out[f"itl_ms_p50_trace_{phase}"] = round(_pct(itls, 0.5), 3)
        out[f"itl_ms_p99_trace_{phase}"] = round(_pct(itls, 0.99), 3)
    out["itl_p50_delta_ms"] = round(
        out["itl_ms_p50_trace_on"] - out["itl_ms_p50_trace_off"], 3)
    out["parity_ok"] = seqs["off"] == seqs["on"]
    return out


# ---------------------------------------------------------------------------
# --overload: offered load > capacity (shed rate + goodput, PR 3)
# ---------------------------------------------------------------------------

async def _bench_overload() -> dict:
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = int(os.environ.get("PENROZ_BENCH_SERVING_BLOCK", "128"))
    rows = int(os.environ.get("PENROZ_BENCH_OVER_ROWS", "2"))
    queue = int(os.environ.get("PENROZ_BENCH_OVER_QUEUE", "2"))
    offered = int(os.environ.get("PENROZ_BENCH_OVER_N", "16"))
    waves = int(os.environ.get("PENROZ_BENCH_OVER_WAVES", "3"))
    max_new = int(os.environ.get("PENROZ_BENCH_MAX_NEW", "16"))
    env = {
        decode_scheduler.ENABLE_ENV: "1",
        decode_scheduler.MAX_ROWS_ENV: str(rows),
        decode_scheduler.MAX_QUEUE_ENV: str(queue),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(0)
    prompts = [[int(t) for t in rng.integers(1, 255, 4 + (i % 4))]
               for i in range(offered)]

    def payload(prompt):
        return {"model_id": "bench-overload", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}

    async def one(prompt):
        t0 = time.perf_counter()
        resp = await client.post("/generate/", json=payload(prompt))
        body = await resp.json() if resp.status != 204 else None
        return resp.status, (time.perf_counter() - t0) * 1000.0, body

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-overload", "layers": _toy_gpt(
                d=128, depth=2, block=block),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        # Solo greedy baselines (scheduler on, no contention) — parity
        # reference for every admitted response under overload.  Also
        # warms every prompt-shape's prefill program.
        baselines = {}
        for p in prompts:
            status, _, body = await one(p)
            assert status == 200, body
            baselines[tuple(p)] = body["tokens"]

        statuses: dict = {}
        latencies = []
        parity_ok = True
        t0 = time.perf_counter()
        completed = 0
        for _ in range(waves):
            results = await asyncio.gather(*[one(p) for p in prompts])
            for p, (status, ms, body) in zip(prompts, results):
                statuses[status] = statuses.get(status, 0) + 1
                if status == 200:
                    completed += 1
                    latencies.append(ms)
                    parity_ok = parity_ok \
                        and body["tokens"] == baselines[tuple(p)]
        wall_s = time.perf_counter() - t0
        shed = statuses.get(429, 0)
        total = sum(statuses.values())
        failures = total - completed - shed

        resp = await client.get("/serving_stats/")
        stats = await resp.json()
        stats.pop("engines", None)
        stats.pop("tick_timeline", None)
        return {
            "mode": "overload", "block_size": block, "capacity_rows": rows,
            "max_queue": queue, "offered_concurrency": offered,
            "waves": waves, "max_new_tokens": max_new,
            "offered_requests": total, "completed": completed,
            "shed_429": shed, "failed_other": failures,
            "shed_rate": round(shed / total, 3) if total else None,
            "goodput_req_per_sec": round(completed / wall_s, 2),
            "goodput_ms_p50": (round(_pct(latencies, 0.5), 3)
                               if latencies else None),
            "goodput_ms_p99": (round(_pct(latencies, 0.99), 3)
                               if latencies else None),
            "parity_ok": parity_ok,
            "serving_stats": stats,
            "metrics_delta": _metrics_delta(
                metrics_before, await _scrape_metrics(client)),
        }
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --replicas: goodput-vs-replicas curve through the router (PR 14)
# ---------------------------------------------------------------------------

async def _bench_replicas() -> dict:
    """Same overload shape as --overload, swept over PENROZ_SCHED_REPLICAS:
    per-replica capacity is fixed, so the group's admitted load — and with
    it goodput — should scale with the replica count while shed rate
    falls.  Prompts are page-aligned shared-prefix families so the
    router's affinity index engages (hit rate in the capture)."""
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = int(os.environ.get("PENROZ_BENCH_SERVING_BLOCK", "128"))
    rows = int(os.environ.get("PENROZ_BENCH_OVER_ROWS", "2"))
    queue = int(os.environ.get("PENROZ_BENCH_OVER_QUEUE", "2"))
    offered = int(os.environ.get("PENROZ_BENCH_OVER_N", "16"))
    waves = int(os.environ.get("PENROZ_BENCH_OVER_WAVES", "3"))
    max_new = int(os.environ.get("PENROZ_BENCH_MAX_NEW", "16"))
    page = int(os.environ.get("PENROZ_BENCH_PREFIX_PAGE", "8"))
    replica_set = [int(r) for r in os.environ.get(
        "PENROZ_BENCH_REPLICA_SET", "1,2,4").split(",")]
    env = {
        decode_scheduler.ENABLE_ENV: "1",
        decode_scheduler.MAX_ROWS_ENV: str(rows),
        decode_scheduler.MAX_QUEUE_ENV: str(queue),
        "PAGED_KV_CACHE": "1",
        "PENROZ_KV_PAGE_SIZE": str(page),
        "PENROZ_PREFIX_CACHE": "1",
        "PENROZ_PREFIX_CACHE_PAGES": "16",
        "PENROZ_SERVE_MESH": "1",
    }
    saved = {k: os.environ.get(k)
             for k in (*env, decode_scheduler.REPLICAS_ENV)}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    # Four shared-prefix families (2 pages each), distinct suffixes: the
    # affinity index steers a family to the replica holding its pages.
    rng = np.random.default_rng(0)
    families = [[int(t) for t in rng.integers(1, 255, 2 * page)]
                for _ in range(4)]
    prompts = [families[i % 4] + [int(t) for t in rng.integers(1, 255, 2)]
               for i in range(offered)]

    def payload(prompt):
        return {"model_id": "bench-replicas", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}

    async def one(prompt):
        t0 = time.perf_counter()
        resp = await client.post("/generate/", json=payload(prompt))
        body = await resp.json() if resp.status != 204 else None
        return resp.status, (time.perf_counter() - t0) * 1000.0, body

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-replicas", "layers": _toy_gpt(
                d=128, depth=2, block=block),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()

        # Solo greedy baselines (1 engine, no contention): the parity
        # reference every admitted response in every phase must match.
        os.environ[decode_scheduler.REPLICAS_ENV] = "1"
        baselines = {}
        for p in prompts:
            status, _, body = await one(p)
            assert status == 200, body
            baselines[tuple(p)] = body["tokens"]

        phases = []
        parity_ok = True
        for n_replicas in replica_set:
            decode_scheduler.reset()  # fresh group at the new width
            os.environ[decode_scheduler.REPLICAS_ENV] = str(n_replicas)
            # Untimed warm wave: spills load across the whole group so
            # every replica compiles its programs before the clock runs.
            await asyncio.gather(*[one(p) for p in prompts])
            statuses: dict = {}
            latencies = []
            completed = 0
            t0 = time.perf_counter()
            for _ in range(waves):
                results = await asyncio.gather(*[one(p) for p in prompts])
                for p, (status, ms, body) in zip(prompts, results):
                    statuses[status] = statuses.get(status, 0) + 1
                    if status == 200:
                        completed += 1
                        latencies.append(ms)
                        parity_ok = parity_ok \
                            and body["tokens"] == baselines[tuple(p)]
            wall_s = time.perf_counter() - t0
            shed = statuses.get(429, 0)
            total = sum(statuses.values())
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            phases.append({
                "replicas": n_replicas,
                "offered_requests": total, "completed": completed,
                "shed_429": shed, "failed_other": total - completed - shed,
                "shed_rate": round(shed / total, 3) if total else None,
                # Per-wave: under a fixed offered load the group admits up
                # to N× one replica's capacity — the scaling replication
                # buys.  Per-second stays honest about the host: replicas
                # on one CPU share cores, on N chips they don't.
                "goodput_req_per_wave": round(completed / waves, 2),
                "goodput_req_per_sec": round(completed / wall_s, 2),
                "goodput_ms_p50": (round(_pct(latencies, 0.5), 3)
                                   if latencies else None),
                "goodput_ms_p99": (round(_pct(latencies, 0.99), 3)
                                   if latencies else None),
                "router_affinity_hits": stats["router_affinity_hits"],
                "router_affinity_misses": stats["router_affinity_misses"],
                "router_affinity_hit_rate": stats["router_affinity_hit_rate"],
                "router_failovers": stats["router_failovers"],
            })

        by_n = {p["replicas"]: p for p in phases}
        speedup = None
        if 1 in by_n and 2 in by_n and by_n[1]["goodput_req_per_wave"]:
            speedup = round(by_n[2]["goodput_req_per_wave"]
                            / by_n[1]["goodput_req_per_wave"], 3)
        return {
            "mode": "replicas", "block_size": block,
            "capacity_rows_per_replica": rows, "max_queue_per_replica": queue,
            "offered_concurrency": offered, "waves": waves,
            "max_new_tokens": max_new, "page_size": page,
            "replica_set": replica_set, "phases": phases,
            "goodput_speedup_2x_vs_1x": speedup,
            "parity_ok": parity_ok,
        }
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --shared-prefix: chunked prefill + radix prefix-KV cache TTFT workload
# ---------------------------------------------------------------------------

def _pct(vals, q: float) -> float:
    """Nearest-rank percentile (q in [0, 1]) of a non-empty list."""
    vals = sorted(vals)
    return vals[min(len(vals) - 1, int(q * len(vals)))]


def _env_i(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


async def _stream_one(client, payload) -> tuple[list[int], float, list[float]]:
    """POST a streaming /generate/; returns (generated tokens, ttft_ms,
    inter-token gaps ms).  TTFT is request-send → first token line — with
    chunked prefill it reflects admission interleaving, not a full-prompt
    stall behind someone else's long prompt."""
    import time as _t
    t0 = _t.perf_counter()
    resp = await client.post("/generate/", json=dict(payload, stream=True))
    assert resp.status == 200, await resp.text()
    toks, stamps = [], []
    while True:
        line = await resp.content.readline()
        if not line:
            break
        toks.append(int(line))
        stamps.append(_t.perf_counter())
    assert toks, "stream produced no tokens"
    ttft_ms = (stamps[0] - t0) * 1000.0
    gaps = [(b - a) * 1000.0 for a, b in zip(stamps, stamps[1:])]
    return toks, ttft_ms, gaps


async def _bench_shared_prefix() -> dict:
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 512)
    d = _env_i("PENROZ_BENCH_SERVING_D", 256)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 4)
    prefix_len = _env_i("PENROZ_BENCH_PREFIX_LEN", 384)
    suffix_len = _env_i("PENROZ_BENCH_SUFFIX_LEN", 4)
    requests = _env_i("PENROZ_BENCH_REQUESTS", 6)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 8)
    page = _env_i("PENROZ_BENCH_PREFIX_PAGE", 16)
    chunk = _env_i("PENROZ_BENCH_CHUNK", 64)
    vocab = 512
    assert prefix_len + suffix_len + max_new <= block

    # Serving-stack env for both phases; PENROZ_PREFIX_CACHE flips per phase.
    cache_pages = 2 * (-(-block // page))  # room for two full prefixes
    env = {
        decode_scheduler.ENABLE_ENV: "1",
        "PAGED_KV_CACHE": "1",
        "PENROZ_KV_PAGE_SIZE": str(page),
        decode_scheduler.PREFILL_CHUNK_ENV: str(chunk),
        "PENROZ_PREFIX_CACHE_PAGES": str(cache_pages),
    }
    saved = {k: os.environ.get(k) for k in (*env, "PENROZ_PREFIX_CACHE")}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(0)
    shared = [int(t) for t in rng.integers(1, vocab - 1, prefix_len)]
    warm = [int(t) for t in rng.integers(1, vocab - 1, prefix_len)]
    suffixes = [[int(t) for t in rng.integers(1, vocab - 1, suffix_len)]
                for _ in range(requests)]

    def payload(prompt):
        return {"model_id": "bench-prefix", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-prefix",
            "layers": _toy_gpt(d=d, vocab=vocab, block=block, depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        results: dict = {
            "mode": "shared_prefix", "block_size": block,
            "prefix_len": prefix_len, "suffix_len": suffix_len,
            "requests": requests, "max_new_tokens": max_new,
            "page_size": page, "prefill_chunk": chunk, "model_d": d,
            "model_depth": depth,
        }
        sequences = {}
        for phase in ("off", "on"):
            os.environ["PENROZ_PREFIX_CACHE"] = "1" if phase == "on" else "0"
            decode_scheduler.reset()  # fresh engine (+ cache) per phase
            # Warm with a DISTINCT prefix: compiles every chunk/decode
            # program so the timed phase measures serving, not XLA; in the
            # 'on' phase it also exercises (and does not pollute) the radix
            # tree — the measured prefix still misses once then hits.
            await _stream_one(client, payload(warm + suffixes[0]))
            ttfts, itls, seqs = [], [], []
            for suffix in suffixes:
                toks, ttft_ms, gaps = await _stream_one(
                    client, payload(shared + suffix))
                ttfts.append(ttft_ms)
                itls.extend(gaps)
                seqs.append(toks)
            sequences[phase] = seqs
            phase_stats = {
                "ttft_ms_p50": round(_pct(ttfts, 0.5), 3),
                "ttft_ms_p99": round(_pct(ttfts, 0.99), 3),
                "ttft_ms_all": [round(t, 3) for t in ttfts],
                "itl_ms_p99": (round(_pct(itls, 0.99), 3) if itls else None),
            }
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            if phase == "on":
                phase_stats["hit_rate"] = stats["prefix_cache_hit_rate"]
                phase_stats["evicted_pages"] = \
                    stats["prefix_cache_evicted_pages"]
            phase_stats["prefill_chunk_stall_ms_p99"] = \
                stats["prefill_chunk_stall_ms_p99"]
            results[f"prefix_cache_{phase}"] = phase_stats
        results["parity_ok"] = sequences["off"] == sequences["on"]
        results["ttft_p50_speedup_on_vs_off"] = round(
            results["prefix_cache_off"]["ttft_ms_p50"]
            / results["prefix_cache_on"]["ttft_ms_p50"], 3)
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --multi-adapter: mixed LoRA tenants in one shared decode batch
# ---------------------------------------------------------------------------

async def _bench_multi_adapter() -> dict:
    """Multi-tenant LoRA workload: N tenants (distinct random adapters +
    the base model) each stream requests; phase 'serial_per_adapter' runs
    one tenant's group at a time (each group still batched — the best a
    per-adapter-engine deployment can do), phase 'mixed' fires every
    tenant concurrently so rows with different adapters share ONE decode
    step via the stacked adapter pack.  Reports wall time + ITL p50/p99
    per phase and asserts greedy parity per request between phases —
    mixing tenants must not change anyone's tokens."""
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import adapters, decode_scheduler

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 256)
    d = _env_i("PENROZ_BENCH_SERVING_D", 256)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 4)
    n_adapters = _env_i("PENROZ_BENCH_LORA_ADAPTERS", 2)
    rank = _env_i("PENROZ_BENCH_LORA_RANK", 8)
    per_tenant = _env_i("PENROZ_BENCH_REQUESTS", 2)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 32)
    prompt_len = _env_i("PENROZ_BENCH_LORA_PROMPT", 8)
    vocab = 512
    assert prompt_len + max_new <= block

    env = {decode_scheduler.ENABLE_ENV: "1",
           decode_scheduler.MAX_ROWS_ENV: str((n_adapters + 1) * per_tenant)}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(0)
    tenants = [f"tenant-{i}" for i in range(n_adapters)] + [None]
    prompts = {t: [[int(x) for x in rng.integers(1, vocab - 1, prompt_len)]
                   for _ in range(per_tenant)] for t in tenants}

    def payload(prompt, tenant):
        p = {"model_id": "bench-lora", "input": [prompt],
             "block_size": block, "max_new_tokens": max_new,
             "temperature": 0.0}
        if tenant is not None:
            p["adapter_id"] = tenant
        return p

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-lora",
            "layers": _toy_gpt(d=d, vocab=vocab, block=block, depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        for i in range(n_adapters):
            resp = await client.post("/adapters/", json={
                "model_id": "bench-lora", "adapter_id": f"tenant-{i}",
                "rank": rank, "init": "random", "seed": 100 + i})
            assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        results: dict = {
            "mode": "multi_adapter", "block_size": block,
            "adapters": n_adapters, "rank": rank,
            "requests_per_tenant": per_tenant, "max_new_tokens": max_new,
            "model_d": d, "model_depth": depth,
        }
        # Warm every (tenant, prompt-shape) program family so the timed
        # phases measure serving, not XLA compiles.
        for t in tenants:
            await _stream_one(client, payload(prompts[t][0], t))

        sequences = {}
        for phase in ("serial_per_adapter", "mixed"):
            decode_scheduler.reset()  # fresh engine + counters per phase
            itls, seqs = [], {}
            t0 = time.perf_counter()
            if phase == "serial_per_adapter":
                for t in tenants:
                    outs = await asyncio.gather(*[
                        _stream_one(client, payload(p, t))
                        for p in prompts[t]])
                    for p, (toks, _, gaps) in zip(prompts[t], outs):
                        itls.extend(gaps)
                        seqs[(t, tuple(p))] = toks
            else:
                jobs = [(t, p) for t in tenants for p in prompts[t]]
                outs = await asyncio.gather(*[
                    _stream_one(client, payload(p, t)) for t, p in jobs])
                for (t, p), (toks, _, gaps) in zip(jobs, outs):
                    itls.extend(gaps)
                    seqs[(t, tuple(p))] = toks
            wall_s = time.perf_counter() - t0
            sequences[phase] = seqs
            results[phase] = {
                "wall_s": round(wall_s, 3),
                "itl_ms_p50": (round(_pct(itls, 0.5), 3) if itls else None),
                "itl_ms_p99": (round(_pct(itls, 0.99), 3) if itls else None),
            }
        results["parity_ok"] = (sequences["serial_per_adapter"]
                                == sequences["mixed"])
        results["wall_speedup_mixed_vs_serial"] = round(
            results["serial_per_adapter"]["wall_s"]
            / results["mixed"]["wall_s"], 3)
        resp = await client.get("/serving_stats/")
        stats = await resp.json()
        stats.pop("engines", None)
        stats.pop("tick_timeline", None)
        results["serving_stats"] = stats
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        adapters.REGISTRY.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --multistep: compiled multi-step decode (tokens/dispatch, ITL)
# ---------------------------------------------------------------------------

async def _bench_multistep() -> dict:
    """Compiled multi-step decode workload (PENROZ_SCHED_SUPERSTEP):
    sequential single-row streaming requests — the regime where the
    per-dispatch host floor is 100% of inter-token latency overhead —
    measured with the superstep at 1 (legacy per-token dispatch loop)
    then 4 and 8.  Reports per-phase **mean ITL** (first→last token wall
    over tokens-1: with fused decode, tokens arrive in blocks of N, so
    gap percentiles are bimodal by design — the mean is the honest
    per-token cost), gap p50/p99 for visibility, and the headline
    **tokens per dispatch** (≈ superstep for unconstrained decode) plus
    ``dispatches_total`` from /serving_stats/.  Greedy parity is asserted
    across every phase — fusing N steps into one program must never
    change a token.  Scale knobs: ``PENROZ_BENCH_SERVING_BLOCK/_D/
    _DEPTH``, ``PENROZ_BENCH_REQUESTS``, ``PENROZ_BENCH_MAX_NEW``,
    ``PENROZ_BENCH_MULTISTEP_PROMPT``."""
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 256)
    d = _env_i("PENROZ_BENCH_SERVING_D", 128)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 2)
    requests = _env_i("PENROZ_BENCH_REQUESTS", 4)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 64)
    prompt_len = _env_i("PENROZ_BENCH_MULTISTEP_PROMPT", 16)
    vocab = 256
    assert prompt_len + max_new <= block

    env = {decode_scheduler.ENABLE_ENV: "1"}
    saved = {k: os.environ.get(k)
             for k in (*env, decode_scheduler.SUPERSTEP_ENV)}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(0)
    prompts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
               for _ in range(requests)]
    warm = [int(t) for t in rng.integers(1, vocab - 1, prompt_len)]

    def payload(prompt):
        return {"model_id": "bench-multistep", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-multistep",
            "layers": _toy_gpt(d=d, vocab=vocab, block=block, depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        results: dict = {
            "mode": "multistep", "block_size": block,
            "prompt_len": prompt_len, "requests": requests,
            "max_new_tokens": max_new, "model_d": d, "model_depth": depth,
        }
        sequences = {}
        for phase, superstep in (("off", 1), ("on4", 4), ("on8", 8)):
            os.environ[decode_scheduler.SUPERSTEP_ENV] = str(superstep)
            decode_scheduler.reset()  # fresh engine (+ counters) per phase
            # Warm with a distinct prompt: compiles the chunk programs and
            # this phase's superstep program so the timed requests measure
            # serving, not XLA.
            await _stream_one(client, payload(warm))
            gaps_all, means, seqs = [], [], []
            for prompt in prompts:
                toks, _, gaps = await _stream_one(client, payload(prompt))
                gaps_all.extend(gaps)
                if gaps:
                    means.append(sum(gaps) / len(gaps))
                seqs.append(toks)
            sequences[phase] = seqs
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            results[f"superstep_{phase}"] = {
                "superstep": superstep,
                "itl_ms_mean": (round(sum(means) / len(means), 3)
                                if means else None),
                "itl_gap_ms_p50": (round(_pct(gaps_all, 0.5), 3)
                                   if gaps_all else None),
                "itl_gap_ms_p99": (round(_pct(gaps_all, 0.99), 3)
                                   if gaps_all else None),
                "dispatches_total": stats["dispatches_total"],
                "tokens_per_dispatch_avg": stats["tokens_per_dispatch_avg"],
                "tokens_per_decode_step": stats["tokens_per_decode_step"],
            }
        results["parity_ok"] = (sequences["off"] == sequences["on4"]
                                == sequences["on8"])
        off_itl = results["superstep_off"]["itl_ms_mean"]
        for phase in ("on4", "on8"):
            on_itl = results[f"superstep_{phase}"]["itl_ms_mean"]
            results[f"itl_mean_speedup_{phase}_vs_off"] = (
                round(off_itl / on_itl, 3) if off_itl and on_itl else None)
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --speculative: prompt-lookup draft + multi-token verify (tokens/step)
# ---------------------------------------------------------------------------

async def _bench_speculative() -> dict:
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler, spec_decode

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 256)
    d = _env_i("PENROZ_BENCH_SERVING_D", 256)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 4)
    requests = _env_i("PENROZ_BENCH_REQUESTS", 4)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 48)
    k = _env_i("PENROZ_BENCH_SPEC_K", 4)
    n = _env_i("PENROZ_BENCH_SPEC_NGRAM", 2)
    prompt_len = _env_i("PENROZ_BENCH_SPEC_PROMPT", 32)
    vocab = _env_i("PENROZ_BENCH_SPEC_VOCAB", 128)
    assert prompt_len + max_new <= block

    env = {
        decode_scheduler.ENABLE_ENV: "1",
        spec_decode.K_ENV: str(k),
        spec_decode.NGRAM_ENV: str(n),
    }
    saved = {key: os.environ.get(key)
             for key in (*env, spec_decode.ENABLE_ENV)}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(0)

    def motif_prompt(seed):
        """Repetitive text: a 4-token motif tiled to prompt_len — the
        trailing n-gram always has earlier occurrences, and greedy toy
        models lock into short cycles the drafter then predicts."""
        motif = [int(t) for t in np.random.default_rng(seed).integers(
            1, vocab - 1, 4)]
        return (motif * (prompt_len // 4 + 1))[:prompt_len]

    prompts = [motif_prompt(100 + i) for i in range(requests)]
    warm = motif_prompt(7)

    def payload(prompt):
        return {"model_id": "bench-spec", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-spec",
            "layers": _toy_gpt(d=d, vocab=vocab, block=block, depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        results: dict = {
            "mode": "speculative", "block_size": block,
            "prompt_len": prompt_len, "requests": requests,
            "max_new_tokens": max_new, "spec_k": k, "spec_ngram": n,
            "vocab": vocab, "model_d": d, "model_depth": depth,
        }
        sequences = {}
        for phase in ("off", "on"):
            os.environ[spec_decode.ENABLE_ENV] = \
                "1" if phase == "on" else "0"
            decode_scheduler.reset()  # fresh engine (+ counters) per phase
            # Warm with a DISTINCT motif: compiles the decode/chunk
            # programs and (on) the verify-program family, so the timed
            # ITLs measure serving, not XLA.
            await _stream_one(client, payload(warm))
            itls, seqs = [], []
            for prompt in prompts:
                toks, _, gaps = await _stream_one(client, payload(prompt))
                itls.extend(gaps)
                seqs.append(toks)
            sequences[phase] = seqs
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            results[f"spec_{phase}"] = {
                "itl_ms_p50": (round(_pct(itls, 0.5), 3) if itls else None),
                "itl_ms_p99": (round(_pct(itls, 0.99), 3) if itls else None),
                "tokens_per_decode_step": stats["tokens_per_decode_step"],
                "spec_accept_rate": stats["spec_accept_rate"],
                "spec_drafted_tokens": stats["spec_drafted_tokens"],
                "spec_accepted_tokens": stats["spec_accepted_tokens"],
            }
        results["parity_ok"] = sequences["off"] == sequences["on"]
        off_tps = results["spec_off"]["tokens_per_decode_step"]
        on_tps = results["spec_on"]["tokens_per_decode_step"]
        results["tokens_per_step_speedup_on_vs_off"] = (
            round(on_tps / off_tps, 3) if off_tps else None)
        results["itl_p50_speedup_on_vs_off"] = round(
            results["spec_off"]["itl_ms_p50"]
            / results["spec_on"]["itl_ms_p50"], 3)
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        for key, v in saved.items():
            if v is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = v


# ---------------------------------------------------------------------------
# --mixed-slo: SLO-tiered QoS (WFQ + preemption + tenant quotas, PR 8)
# ---------------------------------------------------------------------------

async def _bench_mixed_slo() -> dict:
    """Interactive p99 TTFT under a batch flood, FIFO vs QoS.

    Three phases against one small engine (rows/queue deliberately under
    offered load):

    - ``unloaded``: sequential interactive streams, no contention — the
      TTFT yardstick.
    - ``fifo``: flood + probes all submitted classless into the single
      default sub-queue (the pre-QoS scheduler, byte-for-byte) — probes
      queue behind the whole flood.
    - ``qos``: the same offered load, flood tagged ``batch`` and probes
      ``interactive`` — WFQ admission + preempt-to-prefix-cache-resume
      must hold probe TTFT near unloaded while the flood saturates rows.

    Headline fields: ``slo_ok_qos`` (interactive p99 TTFT under QoS
    within the absolute ``PENROZ_BENCH_QOS_SLO_MS`` budget, default
    50 ms, floored at 2× the unloaded p99 so a slow host can't make the
    target unmeetable) and ``slo_exceeded_fifo`` (FIFO blows the budget —
    i.e. the win is real, not slack).  A fourth ``quota`` phase sets a
    tiny token rate for one tenant and fires offender + victim waves:
    only the offender 429s, the victim completes with greedy parity.
    """
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 128)
    rows = _env_i("PENROZ_BENCH_QOS_ROWS", 2)
    flood_n = _env_i("PENROZ_BENCH_QOS_FLOOD", 6)
    # default probes == rows: every probe preempts straight into a row;
    # more probes than rows measures probe-behind-probe wait, not QoS
    probes_n = _env_i("PENROZ_BENCH_QOS_PROBES", rows)
    flood_new = _env_i("PENROZ_BENCH_MAX_NEW", 24)
    probe_new = _env_i("PENROZ_BENCH_QOS_PROBE_NEW", 8)
    env = {
        decode_scheduler.ENABLE_ENV: "1",
        decode_scheduler.MAX_ROWS_ENV: str(rows),
        decode_scheduler.MAX_QUEUE_ENV: "0",       # shedding is not the
        "PAGED_KV_CACHE": "1",                     # phenomenon under test
        "PENROZ_KV_PAGE_SIZE": "16",
        "PENROZ_PREFIX_CACHE": "1",
        "PENROZ_PREFIX_CACHE_PAGES": "64",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(9)
    probe_len = _env_i("PENROZ_BENCH_QOS_PROBE_PROMPT", 24)
    # flood prompts span at least one full KV page (16 tokens) so a victim
    # preempted early still has a whole page to alias on resume
    flood_prompts = [[int(t) for t in rng.integers(1, 255, 18 + (i % 3))]
                     for i in range(flood_n)]
    probe_prompts = [[int(t) for t in rng.integers(1, 255, probe_len)]
                     for _ in range(probes_n)]

    def payload(prompt, max_new, **qos_fields):
        body = {"model_id": "bench-qos", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}
        body.update(qos_fields)
        return body

    async def one(prompt, max_new, **qos_fields):
        resp = await client.post(
            "/generate/", json=payload(prompt, max_new, **qos_fields))
        return resp.status, (await resp.json() if resp.status != 204
                             else None)

    async def probe(prompt, **qos_fields):
        toks, ttft_ms, _ = await _stream_one(
            client, payload(prompt, probe_new, **qos_fields))
        return toks, ttft_ms

    results: dict = {"mode": "mixed_slo", "block_size": block,
                     "capacity_rows": rows, "flood": flood_n,
                     "probes": probes_n, "flood_max_new": flood_new,
                     "probe_max_new": probe_new}
    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-qos", "layers": _toy_gpt(
                d=128, depth=2, block=block),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        # greedy baselines (and program warm-up) for every prompt shape
        flood_base = {}
        for p in flood_prompts:
            status, body = await one(p, flood_new)
            assert status == 200, body
            flood_base[tuple(p)] = body["tokens"]
        probe_base = {}
        for p in probe_prompts:
            toks, _ = await probe(p)
            probe_base[tuple(p)] = toks

        async def saturate(min_queued=0):
            for _ in range(300):
                resp = await client.get("/serving_stats/")
                stats = await resp.json()
                if stats["active_rows"] >= rows \
                        and stats["queue_depth"] >= min_queued:
                    return
                await asyncio.sleep(0.02)

        # Warm the preempt/resume programs BEFORE any measured phase: the
        # first eviction compiles the restored-prefix prefill shape, and
        # that one-time cost must not land inside a probe's measured TTFT.
        warm = [asyncio.ensure_future(one(p, flood_new, priority="batch"))
                for p in flood_prompts[:rows]]
        await saturate()
        await probe(probe_prompts[0], priority="interactive")
        for task in warm:
            status, body = await task
            assert status == 200, body

        # phase 1 — unloaded interactive TTFT yardstick
        ttfts = []
        for p in probe_prompts:
            toks, ttft_ms = await probe(p, priority="interactive")
            assert toks == probe_base[tuple(p)]
            ttfts.append(ttft_ms)
        results["unloaded_ttft_ms_p50"] = round(_pct(ttfts, 0.5), 3)
        results["unloaded_ttft_ms_p99"] = round(_pct(ttfts, 0.99), 3)

        async def loaded_phase(name, flood_fields, probe_fields):
            parity = True
            flood_tasks = [asyncio.ensure_future(
                one(p, flood_new, **flood_fields)) for p in flood_prompts]
            # probes go out only once the flood holds every row AND has a
            # queued backlog — the regime the two phases disagree about
            await saturate(min_queued=1)
            probed = await asyncio.gather(
                *[probe(p, **probe_fields) for p in probe_prompts])
            ttfts = []
            for p, (toks, ttft_ms) in zip(probe_prompts, probed):
                parity = parity and toks == probe_base[tuple(p)]
                ttfts.append(ttft_ms)
            for task, p in zip(flood_tasks, flood_prompts):
                status, body = await task
                assert status == 200, body
                parity = parity and body["tokens"] == flood_base[tuple(p)]
            results[f"{name}_ttft_ms_p50"] = round(_pct(ttfts, 0.5), 3)
            results[f"{name}_ttft_ms_p99"] = round(_pct(ttfts, 0.99), 3)
            results[f"{name}_parity_ok"] = parity

        # phase 2 — FIFO: classless flood AND probes share one sub-queue
        os.environ["PENROZ_QOS_PREEMPT"] = "0"
        await loaded_phase("fifo", {}, {})
        # phase 3 — QoS: same load, SLO classes + preemption armed
        os.environ["PENROZ_QOS_PREEMPT"] = "1"
        await loaded_phase("qos", {"priority": "batch"},
                           {"priority": "interactive"})
        os.environ.pop("PENROZ_QOS_PREEMPT", None)

        # Absolute interactive-TTFT SLO, floored at 2x the unloaded p99 so
        # a slow host never turns the budget into an unmeetable target.
        slo_ms = float(os.environ.get("PENROZ_BENCH_QOS_SLO_MS", "50"))
        budget = max(slo_ms, 2.0 * results["unloaded_ttft_ms_p99"])
        results["ttft_budget_ms"] = round(budget, 3)
        results["slo_ok_qos"] = results["qos_ttft_ms_p99"] < budget
        results["slo_exceeded_fifo"] = results["fifo_ttft_ms_p99"] >= budget

        # phase 4 — tenant quota: only the offender sheds
        rate = _env_i("PENROZ_BENCH_QOS_RATE", 8)
        resp = await client.put("/tenants/offender/quota",
                                json={"tokens_per_s": rate})
        assert resp.status == 200, await resp.text()
        counts = {"offender": {}, "victim": {}}
        parity = True
        for _ in range(3):
            jobs = [one(p, flood_new, tenant=t)
                    for t in ("offender", "victim")
                    for p in flood_prompts[:2]]
            for i, (status, body) in enumerate(await asyncio.gather(*jobs)):
                tenant = "offender" if i < 2 else "victim"
                c = counts[tenant]
                c[status] = c.get(status, 0) + 1
                if status == 200 and tenant == "victim":
                    parity = parity and body["tokens"] == flood_base[
                        tuple(flood_prompts[i - 2])]
        await client.put("/tenants/offender/quota",
                         json={"tokens_per_s": None})
        results["quota"] = {
            "tokens_per_s": rate,
            "offender_statuses": counts["offender"],
            "victim_statuses": counts["victim"],
            "offender_shed": counts["offender"].get(429, 0) > 0,
            "victim_clean": set(counts["victim"]) == {200},
            "victim_parity_ok": parity,
        }

        resp = await client.get("/serving_stats/")
        stats = await resp.json()
        stats.pop("engines", None)
        stats.pop("tick_timeline", None)
        results["preemptions"] = stats.get("preemptions_total", 0)
        results["resume_cached_tokens"] = stats.get(
            "preempted_resume_cached_tokens", 0)
        results["serving_stats"] = stats
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        os.environ.pop("PENROZ_QOS_PREEMPT", None)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --ragged: unified prefill+decode dispatch on mixed traffic (paged vs
# contiguous)
# ---------------------------------------------------------------------------

async def _bench_ragged() -> dict:
    """Mixed-traffic workload for the ragged unified attention path: short
    streaming decodes run concurrently while long prompts arrive and
    chunk-prefill through the SAME engine.  Measured twice:

    - ``contiguous``: PAGED_KV_CACHE=0 — the legacy phased scheduler
      (prefill ticks vs decode ticks, stall budget, superstep fallback
      conditions), the PR 7 baseline behaviour on this traffic.
    - ``paged``: PAGED_KV_CACHE=1 — the unified ragged path, where one
      dispatch carries prefill chunks, decode steps, and (with spec on)
      verify rows in a single descriptor grid.

    Headlines: per-phase **mixed ITL p50/p99** of the decode streams (the
    latency prefill chunks used to stall), **tokens per dispatch** and
    ``dispatches_total`` (deterministic counters — the unified path must
    emit more tokens per host round-trip than phased scheduling on the
    same offered load), greedy parity between phases, and — from the tick
    timeline — ``mixed_ticks`` / ``mixed_fused_superstep_max``: unified
    ticks whose single dispatch carried BOTH prefill chunks and shared
    decode rows at superstep > 1, the regime every PR 7 fallback
    condition used to kick the engine back to one-step dispatches.
    Scale knobs: ``PENROZ_BENCH_RAGGED_STREAMS/_PREFILLS/_PROMPT/_LONG/
    _PREFILL_NEW`` plus the shared ``PENROZ_BENCH_SERVING_BLOCK/_D/
    _DEPTH`` / ``PENROZ_BENCH_MAX_NEW`` / ``PENROZ_BENCH_CHUNK`` set."""
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 256)
    d = _env_i("PENROZ_BENCH_SERVING_D", 128)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 2)
    streams = _env_i("PENROZ_BENCH_RAGGED_STREAMS", 3)
    prefills = _env_i("PENROZ_BENCH_RAGGED_PREFILLS", 3)
    prompt_len = _env_i("PENROZ_BENCH_RAGGED_PROMPT", 12)
    long_len = _env_i("PENROZ_BENCH_RAGGED_LONG", 160)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 48)
    prefill_new = _env_i("PENROZ_BENCH_RAGGED_PREFILL_NEW", 4)
    chunk = _env_i("PENROZ_BENCH_CHUNK", 32)
    vocab = 256
    assert prompt_len + max_new <= block
    assert long_len + prefill_new <= block

    env = {
        decode_scheduler.ENABLE_ENV: "1",
        decode_scheduler.MAX_ROWS_ENV: str(streams + prefills),
        decode_scheduler.PREFILL_CHUNK_ENV: str(chunk),
        "PENROZ_KV_PAGE_SIZE": "16",
    }
    saved = {k: os.environ.get(k) for k in (*env, "PAGED_KV_CACHE")}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(11)
    short_prompts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
                     for _ in range(streams)]
    long_prompts = [[int(t) for t in rng.integers(1, vocab - 1, long_len)]
                    for _ in range(prefills)]
    warm_shorts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
                   for _ in range(streams)]
    warm_longs = [[int(t) for t in rng.integers(1, vocab - 1, long_len)]
                  for _ in range(prefills)]

    def payload(prompt, new):
        return {"model_id": "bench-ragged", "input": [prompt],
                "block_size": block, "max_new_tokens": new,
                "temperature": 0.0}

    async def saturate(n):
        for _ in range(300):
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            if stats["active_rows"] >= n:
                return
            await asyncio.sleep(0.01)

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-ragged",
            "layers": _toy_gpt(d=d, vocab=vocab, block=block, depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        results: dict = {
            "mode": "ragged", "block_size": block, "streams": streams,
            "prefills": prefills, "stream_prompt_len": prompt_len,
            "long_prompt_len": long_len, "stream_max_new": max_new,
            "prefill_max_new": prefill_new, "prefill_chunk": chunk,
            "model_d": d, "model_depth": depth,
        }
        sequences = {}
        for phase in ("contiguous", "paged"):
            os.environ["PAGED_KV_CACHE"] = "1" if phase == "paged" else "0"
            decode_scheduler.reset()  # fresh engine + KV layout per phase
            # Warm with DISTINCT prompts at the MEASURED composition
            # (streams short decodes + prefills long prompts concurrently):
            # the mixed-program shape families (n steps x descriptor-block
            # buckets) depend on the batch mix, so a single-request warm-up
            # would leave the measured phase paying XLA compiles.  Which
            # shapes a round exercises is timing-dependent, so repeat until
            # the penroz_jit_programs gauge stops growing — steady state by
            # the compile-churn guard's own definition.
            programs = -1
            for _ in range(5):
                warm_stream = [asyncio.ensure_future(
                    _stream_one(client, payload(p, max_new)))
                    for p in warm_shorts]
                await saturate(streams)
                await asyncio.gather(
                    *warm_stream,
                    *[_stream_one(client, payload(p, prefill_new))
                      for p in warm_longs])
                scrape = await _scrape_metrics(client)
                now_programs = sum(v for k, v in scrape.items()
                                   if k.startswith("penroz_jit_programs"))
                if now_programs == programs:
                    break
                programs = now_programs
            # Measured: decode streams first, long prefills land mid-flight.
            stream_tasks = [asyncio.ensure_future(
                _stream_one(client, payload(p, max_new)))
                for p in short_prompts]
            await saturate(streams)
            t0 = time.perf_counter()
            long_tasks = [asyncio.ensure_future(
                _stream_one(client, payload(p, prefill_new)))
                for p in long_prompts]
            stream_out = await asyncio.gather(*stream_tasks)
            long_out = await asyncio.gather(*long_tasks)
            wall_s = time.perf_counter() - t0
            itls, seqs = [], []
            for toks, _, gaps in stream_out:
                itls.extend(gaps)
                seqs.append(toks)
            long_ttfts = []
            for toks, ttft_ms, _ in long_out:
                long_ttfts.append(ttft_ms)
                seqs.append(toks)
            sequences[phase] = seqs
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            timeline = stats.get("tick_timeline") or []
            mixed = [e for e in timeline
                     if e.get("prefill_chunks", 0) > 0
                     and e.get("shared_rows", 0) > 0]
            results[phase] = {
                # fused dispatches deliver tokens in bursts, so gap
                # percentiles are bimodal by design — the mean is the
                # honest per-token cost, percentiles shown for visibility
                "mixed_itl_ms_mean": (round(sum(itls) / len(itls), 3)
                                      if itls else None),
                "mixed_itl_ms_p50": (round(_pct(itls, 0.5), 3)
                                     if itls else None),
                "mixed_itl_ms_p99": (round(_pct(itls, 0.99), 3)
                                     if itls else None),
                "long_ttft_ms_p50": round(_pct(long_ttfts, 0.5), 3),
                "wall_s": round(wall_s, 3),
                "dispatches_total": stats["dispatches_total"],
                "tokens_per_dispatch_avg": stats["tokens_per_dispatch_avg"],
                "prefill_chunk_stall_ms_p99":
                    stats["prefill_chunk_stall_ms_p99"],
                "unified_ticks": sum(1 for e in timeline
                                     if e.get("unified")),
                "mixed_ticks": len(mixed),
                "mixed_fused_superstep_max": max(
                    (e.get("superstep", 1) for e in mixed), default=0),
            }
        results["parity_ok"] = sequences["contiguous"] == sequences["paged"]
        cont, paged = results["contiguous"], results["paged"]
        results["tokens_per_dispatch_paged_vs_contiguous"] = (
            round(paged["tokens_per_dispatch_avg"]
                  / cont["tokens_per_dispatch_avg"], 3)
            if cont["tokens_per_dispatch_avg"] else None)
        results["mixed_itl_p99_contiguous_vs_paged"] = (
            round(cont["mixed_itl_ms_p99"] / paged["mixed_itl_ms_p99"], 3)
            if cont["mixed_itl_ms_p99"] and paged["mixed_itl_ms_p99"]
            else None)
        # the acceptance gate: paged is the fast path on mixed traffic —
        # more tokens per host round-trip (deterministic counters), never
        # bought with wrong tokens
        results["paged_ge_contiguous"] = bool(
            results["parity_ok"]
            and paged["tokens_per_dispatch_avg"]
            >= cont["tokens_per_dispatch_avg"])
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --disagg: dedicated prefill replicas streaming KV pages to decode replicas
# ---------------------------------------------------------------------------

async def _bench_disagg() -> dict:
    """Disaggregated-prefill workload (serve/router.py phase steering +
    the decode_scheduler export/import hand-off): interactive decode
    streams and long prompts share a 2-replica group, measured twice:

    - ``colocated``: PENROZ_DISAGG_PREFILL=0 — the PR 14 router, every
      replica admits, prefills and decodes; least-loaded placement puts
      long-prompt chunk prefills on the same engines as the streams, so
      stream token gaps absorb chunk dispatches.
    - ``disagg``: PENROZ_DISAGG_PREFILL=1 — replica 0 (role ``prefill``)
      runs every prompt's prefill to completion and exports the finished
      KV pages as a page blob; replica 1 (role ``decode``) imports the
      blob into its own pool and decodes.  The decode replica's tick
      loop never executes a prefill chunk — asserted via its
      ``prefill_chunks`` counter, not timing.

    Headlines: per-phase **decode ITL p50/p99** of the streams, long
    TTFT p50/p99 (disagg pays the hand-off inside it), hand-off latency
    p50/p99 + export/import/failure counters from the serving stats,
    and tokens per dispatch split by replica role.  The hand-off
    percentiles are cumulative over the phase, so the p99 includes the
    warm-up's one-time import compile; ``disagg_handoff_ms_mean_measured``
    (metrics delta over the timed window only) is the steady-state
    number.  Greedy parity is asserted between phases — the hand-off
    must never trade tokens for latency.  ``ok`` gates on parity + every
    request imported + zero failures + a chunk-free decode replica."""
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 384)
    d = _env_i("PENROZ_BENCH_SERVING_D", 128)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 2)
    streams = _env_i("PENROZ_BENCH_DISAGG_STREAMS", 3)
    prefills = _env_i("PENROZ_BENCH_DISAGG_PREFILLS", 2)
    prompt_len = _env_i("PENROZ_BENCH_DISAGG_PROMPT", 12)
    long_len = _env_i("PENROZ_BENCH_DISAGG_LONG", 256)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 32)
    prefill_new = _env_i("PENROZ_BENCH_DISAGG_PREFILL_NEW", 4)
    rounds = _env_i("PENROZ_BENCH_DISAGG_ROUNDS", 3)
    chunk = _env_i("PENROZ_BENCH_CHUNK", 32)
    page = _env_i("PENROZ_BENCH_PREFIX_PAGE", 16)
    vocab = 256
    assert prompt_len + max_new <= block
    assert long_len + prefill_new <= block

    env = {
        decode_scheduler.ENABLE_ENV: "1",
        decode_scheduler.MAX_ROWS_ENV: str(streams + prefills),
        decode_scheduler.PREFILL_CHUNK_ENV: str(chunk),
        decode_scheduler.REPLICAS_ENV: "2",
        "PAGED_KV_CACHE": "1",
        "PENROZ_KV_PAGE_SIZE": str(page),
        "PENROZ_DISAGG_PREFILL_REPLICAS": "1",
    }
    saved = {k: os.environ.get(k)
             for k in (*env, "PENROZ_DISAGG_PREFILL")}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(23)
    short_prompts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
                     for _ in range(streams)]
    long_prompts = [[int(t) for t in rng.integers(1, vocab - 1, long_len)]
                    for _ in range(prefills)]
    warm_shorts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
                   for _ in range(streams)]
    warm_longs = [[int(t) for t in rng.integers(1, vocab - 1, long_len)]
                  for _ in range(prefills)]

    def payload(prompt, new):
        return {"model_id": "bench-disagg", "input": [prompt],
                "block_size": block, "max_new_tokens": new,
                "temperature": 0.0}

    async def saturate(n):
        for _ in range(300):
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            if stats["active_rows"] >= n:
                return
            await asyncio.sleep(0.01)

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-disagg",
            "layers": _toy_gpt(d=d, vocab=vocab, block=block, depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        results: dict = {
            "mode": "disagg", "block_size": block, "replicas": 2,
            "prefill_replicas": 1, "streams": streams,
            "prefills": prefills, "stream_prompt_len": prompt_len,
            "long_prompt_len": long_len, "stream_max_new": max_new,
            "prefill_max_new": prefill_new, "prefill_chunk": chunk,
            "page_size": page, "measured_rounds": rounds,
            "model_d": d, "model_depth": depth,
        }
        sequences = {}
        for phase in ("colocated", "disagg"):
            os.environ["PENROZ_DISAGG_PREFILL"] = (
                "1" if phase == "disagg" else "0")
            decode_scheduler.reset()  # fresh group + roles per phase
            # Warm at the MEASURED composition until the jit-programs
            # gauge stops growing (same rationale as --ragged: the mixed
            # shape families depend on the batch mix, and here also on
            # which replica a row decodes on).  Which shapes a round
            # exercises is timing-dependent across TWO engines, so demand
            # two consecutive stable rounds before trusting steady state.
            programs, stable = -1, 0
            for _ in range(8):
                warm_stream = [asyncio.ensure_future(
                    _stream_one(client, payload(p, max_new)))
                    for p in warm_shorts]
                await saturate(streams)
                await asyncio.gather(
                    *warm_stream,
                    *[_stream_one(client, payload(p, prefill_new))
                      for p in warm_longs])
                scrape = await _scrape_metrics(client)
                now_programs = sum(v for k, v in scrape.items()
                                   if k.startswith("penroz_jit_programs"))
                stable = stable + 1 if now_programs == programs else 0
                if stable >= 2:
                    break
                programs = now_programs
            # Measured: streams decode first, long prompts land mid-flight.
            # Pooled over several rounds so the tail percentiles reflect
            # the stall POPULATIONS (chunk dispatches vs hand-off imports)
            # rather than one unlucky scheduling event.  Which shapes run
            # is timing-dependent, so a straggler compile can still land
            # inside the window — it stalls every stream at once for ~1s,
            # poisoning the pooled tail with churn rather than
            # steady-state serving.  Detected via the jit-programs gauge
            # and re-measured (the program is warm on the retry).
            for attempt in range(3):
                scrape_pre = await _scrape_metrics(client)
                programs_pre = sum(v for k, v in scrape_pre.items()
                                   if k.startswith("penroz_jit_programs"))
                itls, long_ttfts, seqs = [], [], []
                wall_s = 0.0
                for _ in range(rounds):
                    stream_tasks = [asyncio.ensure_future(
                        _stream_one(client, payload(p, max_new)))
                        for p in short_prompts]
                    await saturate(streams)
                    t0 = time.perf_counter()
                    long_tasks = [asyncio.ensure_future(
                        _stream_one(client, payload(p, prefill_new)))
                        for p in long_prompts]
                    stream_out = await asyncio.gather(*stream_tasks)
                    long_out = await asyncio.gather(*long_tasks)
                    wall_s += time.perf_counter() - t0
                    for toks, _, gaps in stream_out:
                        itls.extend(gaps)
                        seqs.append(toks)
                    for toks, ttft_ms, _ in long_out:
                        long_ttfts.append(ttft_ms)
                        seqs.append(toks)
                scrape_post = await _scrape_metrics(client)
                programs_post = sum(v for k, v in scrape_post.items()
                                    if k.startswith("penroz_jit_programs"))
                if programs_post == programs_pre:
                    break
            sequences[phase] = seqs
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            per = stats.get("engines") or []
            h_sum = (scrape_post.get("penroz_disagg_handoff_ms_sum", 0.0)
                     - scrape_pre.get("penroz_disagg_handoff_ms_sum", 0.0))
            h_cnt = (scrape_post.get("penroz_disagg_handoff_ms_count", 0.0)
                     - scrape_pre.get("penroz_disagg_handoff_ms_count", 0.0))
            results[phase] = {
                "roles": [e.get("role", "decode") for e in per],
                "decode_itl_ms_mean": (round(sum(itls) / len(itls), 3)
                                       if itls else None),
                "decode_itl_ms_p50": (round(_pct(itls, 0.5), 3)
                                      if itls else None),
                "decode_itl_ms_p99": (round(_pct(itls, 0.99), 3)
                                      if itls else None),
                "long_ttft_ms_p50": round(_pct(long_ttfts, 0.5), 3),
                "long_ttft_ms_p99": round(_pct(long_ttfts, 0.99), 3),
                "wall_s": round(wall_s, 3),
                "prefill_chunks_by_replica": [
                    e.get("prefill_chunks", 0) for e in per],
                # chunk work on a decode-role replica breaks the whole
                # point — counted, not timed
                "decode_replica_prefill_chunks": sum(
                    e.get("prefill_chunks", 0) for e in per
                    if e.get("role", "decode") == "decode"),
                "decode_tokens_per_dispatch": [
                    e.get("tokens_per_dispatch_avg") for e in per
                    if e.get("role", "decode") == "decode"],
                "disagg_exports": stats.get("disagg_exports", 0),
                "disagg_imports": stats.get("disagg_imports", 0),
                "disagg_handoff_failures": stats.get(
                    "disagg_handoff_failures", 0),
                "disagg_handoff_ms_p50": stats.get("disagg_handoff_ms_p50"),
                "disagg_handoff_ms_p99": stats.get("disagg_handoff_ms_p99"),
                "disagg_handoff_ms_mean_measured": (
                    round(h_sum / h_cnt, 3) if h_cnt else None),
                "handoffs_measured": int(h_cnt),
                "measure_attempts": attempt + 1,
                "measured_compiles": int(programs_post - programs_pre),
            }
        results["parity_ok"] = sequences["colocated"] == sequences["disagg"]
        col, dis = results["colocated"], results["disagg"]
        results["decode_itl_p99_colocated_vs_disagg"] = (
            round(col["decode_itl_ms_p99"] / dis["decode_itl_ms_p99"], 3)
            if col["decode_itl_ms_p99"] and dis["decode_itl_ms_p99"]
            else None)
        results["itl_p99_improved"] = bool(
            col["decode_itl_ms_p99"] is not None
            and dis["decode_itl_ms_p99"] is not None
            and dis["decode_itl_ms_p99"] <= col["decode_itl_ms_p99"])
        results["ok"] = bool(
            results["parity_ok"]
            and dis["roles"] == ["prefill", "decode"]
            and dis["disagg_imports"] >= streams + prefills
            and dis["disagg_exports"] == dis["disagg_imports"]
            and dis["disagg_handoff_failures"] == 0
            and dis["decode_replica_prefill_chunks"] == 0
            and col["disagg_imports"] == 0)
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --disagg-elastic: d2d vs host hand-off transport + elastic role flips
# ---------------------------------------------------------------------------

def _hist_pct_delta(pre: dict, post: dict, name: str, q: float):
    """Nearest-bucket-upper-bound percentile of a histogram's GROWTH
    between two scrapes — the measured window only, so warm-up compiles
    never poison a transport comparison.  Same quantile convention as the
    engine stats percentiles (utils/metrics.quantile_of), minus the
    observed-max clamp the exposition cannot carry."""
    prefix = name + '_bucket{le="'
    buckets = []
    for key, value in post.items():
        if key.startswith(prefix):
            buckets.append((float(key[len(prefix):-2]),
                            value - pre.get(key, 0.0)))
    buckets.sort()
    if not buckets or buckets[-1][1] <= 0:
        return None
    target = q * buckets[-1][1]
    finite = [e for e, _ in buckets if e != float("inf")]
    for edge, cum in buckets:
        if cum >= target:
            # +Inf bucket -> clamp to the largest finite edge (JSON-safe,
            # matching quantile_of's observed-max clamp in spirit)
            return edge if edge != float("inf") else (
                finite[-1] if finite else None)
    return finite[-1] if finite else None


async def _bench_disagg_elastic() -> dict:
    """Device-to-device hand-off + elastic prefill/decode split (PR 16),
    two phases over the --disagg workload shape:

    A. **Transport** (2 replicas, PENROZ_DISAGG_PREFILL=1): the same
       long-prompt hand-off burst measured once per
       ``PENROZ_DISAGG_TRANSPORT`` in {host, d2d} — hand-offs ONLY, no
       interactive streams, so the decode replica admits each import
       immediately and the measured time is the transport, not
       admission wait.  Greedy parity is asserted across transports;
       hand-off latency p50/p99 comes from the
       ``penroz_disagg_handoff_ms`` histogram delta over the timed
       window.  Gate: d2d p99 < host p99 — handing device arrays across
       engines must beat serialize + CRC + shm staging + deserialize.
    B. **Elastic** (3 replicas): each round is a prefill burst (long
       prompts, tiny decode) followed by a decode burst (interactive
       streams), run once pinned (PENROZ_DISAGG_ELASTIC=0) and once
       elastic with an eager cooldown.  Greedy parity asserted; decode
       ITL p99 compared (elastic must be no worse than pinned within
       10%); the elastic run must actually flip roles
       (``disagg_role_changes`` > 0, pinned == 0).

    Strict memledger throughout: a page leaked across the d2d ack seam or
    a role flip raises in the engine worker and fails the bench."""
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 384)
    d = _env_i("PENROZ_BENCH_SERVING_D", 128)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 2)
    streams = _env_i("PENROZ_BENCH_D2D_STREAMS", 3)
    handoffs = _env_i("PENROZ_BENCH_D2D_HANDOFFS", 4)
    prompt_len = _env_i("PENROZ_BENCH_D2D_PROMPT", 12)
    long_len = _env_i("PENROZ_BENCH_D2D_LONG", 256)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 24)
    prefill_new = _env_i("PENROZ_BENCH_D2D_PREFILL_NEW", 4)
    rounds = _env_i("PENROZ_BENCH_D2D_ROUNDS", 2)
    chunk = _env_i("PENROZ_BENCH_CHUNK", 64)
    page = _env_i("PENROZ_BENCH_PREFIX_PAGE", 16)
    vocab = 256
    assert prompt_len + max_new <= block
    assert long_len + prefill_new <= block

    env = {
        decode_scheduler.ENABLE_ENV: "1",
        decode_scheduler.MAX_ROWS_ENV: str(streams + handoffs),
        decode_scheduler.PREFILL_CHUNK_ENV: str(chunk),
        "PAGED_KV_CACHE": "1",
        "PENROZ_KV_PAGE_SIZE": str(page),
        "PENROZ_MEMLEDGER_STRICT": "1",
        "PENROZ_DISAGG_PREFILL": "1",
        "PENROZ_DISAGG_PREFILL_REPLICAS": "1",
    }
    saved = {k: os.environ.get(k)
             for k in (*env, decode_scheduler.REPLICAS_ENV,
                       decode_scheduler.DISAGG_TRANSPORT_ENV,
                       "PENROZ_DISAGG_ELASTIC",
                       "PENROZ_DISAGG_REBALANCE_COOLDOWN_MS")}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(61)
    short_prompts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
                     for _ in range(streams)]
    long_prompts = [[int(t) for t in rng.integers(1, vocab - 1, long_len)]
                    for _ in range(handoffs)]
    warm_shorts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
                   for _ in range(streams)]
    warm_longs = [[int(t) for t in rng.integers(1, vocab - 1, long_len)]
                  for _ in range(handoffs)]

    def payload(prompt, new):
        return {"model_id": "bench-d2d", "input": [prompt],
                "block_size": block, "max_new_tokens": new,
                "temperature": 0.0}

    async def warm_until_stable(shorts=True):
        programs, stable = -1, 0
        for _ in range(8):
            await asyncio.gather(
                *[_stream_one(client, payload(p, max_new))
                  for p in (warm_shorts if shorts else [])],
                *[_stream_one(client, payload(p, prefill_new))
                  for p in warm_longs])
            scrape = await _scrape_metrics(client)
            now_programs = sum(v for k, v in scrape.items()
                               if k.startswith("penroz_jit_programs"))
            stable = stable + 1 if now_programs == programs else 0
            if stable >= 2:
                return
            programs = now_programs

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-d2d",
            "layers": _toy_gpt(d=d, vocab=vocab, block=block, depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        results: dict = {
            "mode": "disagg_elastic", "block_size": block,
            "streams": streams, "handoffs_per_round": handoffs,
            "stream_prompt_len": prompt_len, "long_prompt_len": long_len,
            "stream_max_new": max_new, "prefill_max_new": prefill_new,
            "prefill_chunk": chunk, "page_size": page,
            "measured_rounds": rounds, "model_d": d, "model_depth": depth,
        }

        # -- phase A: hand-off transport, host vs d2d -----------------------
        os.environ[decode_scheduler.REPLICAS_ENV] = "2"
        transport_seqs = {}
        results["transport"] = {}
        for transport in ("host", "d2d"):
            os.environ[decode_scheduler.DISAGG_TRANSPORT_ENV] = transport
            decode_scheduler.reset()
            # long hand-offs ONLY: no interactive streams means the decode
            # replica is idle when an export lands, so the measured
            # hand-off time is transfer + scatter rather than admission
            # wait behind busy decode ticks (which is transport-blind
            # noise that buries the codec-cost difference in the tail)
            await warm_until_stable(shorts=False)
            # a straggler compile inside the measured window stalls every
            # hand-off at once and poisons a small-sample p99 — detected
            # via the jit-programs gauge and re-measured (warm on retry)
            for attempt in range(3):
                scrape_pre = await _scrape_metrics(client)
                programs_pre = sum(v for k, v in scrape_pre.items()
                                   if k.startswith("penroz_jit_programs"))
                seqs = []
                for _ in range(rounds):
                    out = await asyncio.gather(
                        *[_stream_one(client, payload(p, prefill_new))
                          for p in long_prompts])
                    for toks, _, _gaps in out:
                        seqs.append(toks)
                scrape_post = await _scrape_metrics(client)
                programs_post = sum(v for k, v in scrape_post.items()
                                    if k.startswith("penroz_jit_programs"))
                if programs_post == programs_pre:
                    break
            transport_seqs[transport] = seqs
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            h_sum = (scrape_post.get("penroz_disagg_handoff_ms_sum", 0.0)
                     - scrape_pre.get("penroz_disagg_handoff_ms_sum", 0.0))
            h_cnt = (scrape_post.get("penroz_disagg_handoff_ms_count", 0.0)
                     - scrape_pre.get("penroz_disagg_handoff_ms_count", 0.0))
            b_sum = (scrape_post.get("penroz_disagg_handoff_bytes_sum", 0.0)
                     - scrape_pre.get("penroz_disagg_handoff_bytes_sum",
                                      0.0))
            results["transport"][transport] = {
                "roles": [e.get("role") for e in stats.get("engines", [])],
                "handoffs_measured": int(h_cnt),
                "handoff_ms_p50": _hist_pct_delta(
                    scrape_pre, scrape_post,
                    "penroz_disagg_handoff_ms", 0.5),
                "handoff_ms_p99": _hist_pct_delta(
                    scrape_pre, scrape_post,
                    "penroz_disagg_handoff_ms", 0.99),
                "handoff_ms_mean": (round(h_sum / h_cnt, 3)
                                    if h_cnt else None),
                "handoff_bytes_mean": (round(b_sum / h_cnt)
                                       if h_cnt else None),
                "disagg_exports": stats.get("disagg_exports", 0),
                "disagg_imports": stats.get("disagg_imports", 0),
                "disagg_handoff_failures": stats.get(
                    "disagg_handoff_failures", 0),
                "disagg_transport": stats.get("disagg_transport"),
                "measure_attempts": attempt + 1,
                "measured_compiles": int(programs_post - programs_pre),
            }
        host, d2d = (results["transport"]["host"],
                     results["transport"]["d2d"])
        results["transport"]["parity_ok"] = (
            transport_seqs["host"] == transport_seqs["d2d"])
        results["transport"]["handoff_p99_improved"] = bool(
            host["handoff_ms_p99"] is not None
            and d2d["handoff_ms_p99"] is not None
            and d2d["handoff_ms_p99"] < host["handoff_ms_p99"])
        results["transport"]["handoff_mean_ratio_host_vs_d2d"] = (
            round(host["handoff_ms_mean"] / d2d["handoff_ms_mean"], 3)
            if host["handoff_ms_mean"] and d2d["handoff_ms_mean"]
            else None)

        # -- phase B: prefill burst -> decode burst, pinned vs elastic ------
        os.environ[decode_scheduler.REPLICAS_ENV] = "3"
        os.environ[decode_scheduler.DISAGG_TRANSPORT_ENV] = "d2d"
        elastic_seqs = {}
        results["elastic"] = {}
        for kind in ("pinned", "elastic"):
            os.environ["PENROZ_DISAGG_ELASTIC"] = (
                "1" if kind == "elastic" else "0")
            os.environ["PENROZ_DISAGG_REBALANCE_COOLDOWN_MS"] = "200"
            decode_scheduler.reset()
            await warm_until_stable()
            for attempt in range(3):
                scrape_pre = await _scrape_metrics(client)
                programs_pre = sum(v for k, v in scrape_pre.items()
                                   if k.startswith("penroz_jit_programs"))
                seqs, itls = [], []
                for _ in range(rounds):
                    # prefill burst: the backlog signal the rebalancer reads
                    burst = await asyncio.gather(
                        *[_stream_one(client, payload(p, prefill_new))
                          for p in long_prompts])
                    # decode burst: interactive streams on the drained group
                    decode = await asyncio.gather(
                        *[_stream_one(client, payload(p, max_new))
                          for p in short_prompts])
                    for toks, _, _gaps in burst:
                        seqs.append(toks)
                    for toks, _, gaps in decode:
                        seqs.append(toks)
                        itls.extend(gaps)
                scrape_post = await _scrape_metrics(client)
                programs_post = sum(v for k, v in scrape_post.items()
                                    if k.startswith("penroz_jit_programs"))
                if programs_post == programs_pre:
                    break
            elastic_seqs[kind] = seqs
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            results["elastic"][kind] = {
                "roles": [e.get("role") for e in stats.get("engines", [])],
                "decode_itl_ms_p50": (round(_pct(itls, 0.5), 3)
                                      if itls else None),
                "decode_itl_ms_p99": (round(_pct(itls, 0.99), 3)
                                      if itls else None),
                "disagg_role_changes": stats.get("disagg_role_changes", 0),
                "disagg_imports": stats.get("disagg_imports", 0),
                "disagg_handoff_failures": stats.get(
                    "disagg_handoff_failures", 0),
                "measure_attempts": attempt + 1,
                "measured_compiles": int(programs_post - programs_pre),
            }
        pinned, elastic = (results["elastic"]["pinned"],
                           results["elastic"]["elastic"])
        results["elastic"]["parity_ok"] = (
            elastic_seqs["pinned"] == elastic_seqs["elastic"])
        results["elastic"]["itl_p99_elastic_vs_pinned"] = (
            round(elastic["decode_itl_ms_p99"]
                  / pinned["decode_itl_ms_p99"], 3)
            if elastic["decode_itl_ms_p99"] and pinned["decode_itl_ms_p99"]
            else None)
        results["elastic"]["itl_p99_no_worse"] = bool(
            elastic["decode_itl_ms_p99"] is not None
            and pinned["decode_itl_ms_p99"] is not None
            and elastic["decode_itl_ms_p99"]
            <= pinned["decode_itl_ms_p99"] * 1.10)

        # wiring_ok is the structural gate (parity, exactly-once hand-off,
        # role flips only when elastic) — what a CPU smoke can hold against
        # scheduler noise.  ok adds the timing claims (d2d p99 beats host,
        # elastic ITL no worse) the committed capture exists to evidence.
        results["wiring_ok"] = bool(
            results["transport"]["parity_ok"]
            and host["disagg_handoff_failures"] == 0
            and d2d["disagg_handoff_failures"] == 0
            and host["disagg_imports"] == host["disagg_exports"] > 0
            and d2d["disagg_imports"] == d2d["disagg_exports"] > 0
            and results["elastic"]["parity_ok"]
            and elastic["disagg_role_changes"] > 0
            and pinned["disagg_role_changes"] == 0)
        results["ok"] = bool(
            results["wiring_ok"]
            and results["transport"]["handoff_p99_improved"]
            and results["elastic"]["itl_p99_no_worse"])
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --memory: capacity-ledger overhead + mixed-tenant attribution
# ---------------------------------------------------------------------------

async def _bench_memory() -> dict:
    """Capacity-ledger workload (serve/memledger.py): the ledger derives
    ownership at read time, so its cost must be invisible on the decode
    path.  Phase one streams sequential requests with PENROZ_MEMLEDGER=0
    then =1 (greedy parity asserted, ITL delta recorded — the acceptance
    bar is 'within noise', so the capture records evidence, not a flaky
    threshold).  Phase two fires two tenants concurrently and polls
    ``GET /memory/`` while they decode: both tenants must show up with
    nonzero page counts and every engine's page states must sum to its
    pool capacity on every poll.  Runs STRICT (a leaked page raises in
    the worker and fails the bench), and the final snapshot must carry
    zero audit failures, pool drops, and unpin underflows."""
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler, memledger

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 256)
    d = _env_i("PENROZ_BENCH_SERVING_D", 256)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 4)
    per_tenant = _env_i("PENROZ_BENCH_REQUESTS", 3)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 32)
    page = _env_i("PENROZ_BENCH_MEM_PAGE", 16)
    prompt_len = _env_i("PENROZ_BENCH_MEM_PROMPT", 24)
    vocab = 512
    assert prompt_len + max_new <= block

    env = {decode_scheduler.ENABLE_ENV: "1",
           "PAGED_KV_CACHE": "1",
           "PENROZ_KV_PAGE_SIZE": str(page),
           "PENROZ_PREFIX_CACHE": "1",
           memledger.STRICT_ENV: "1"}
    saved = {k: os.environ.get(k) for k in (*env, memledger.ENABLE_ENV)}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(0)
    prompts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
               for _ in range(2 * per_tenant)]

    def payload(prompt, tenant=None):
        body = {"model_id": "bench-mem", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}
        if tenant is not None:
            body["tenant"] = tenant
        return body

    results: dict = {"mode": "memory", "block_size": block,
                     "page_size": page, "requests_per_tenant": per_tenant,
                     "max_new_tokens": max_new, "prompt_len": prompt_len,
                     "model_d": d, "model_depth": depth}
    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-mem",
            "layers": _toy_gpt(d=d, vocab=vocab, block=block, depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        # -- phase 1: ledger on/off ITL (warm per mode: the compile and
        # prefix-cache state must not masquerade as ledger cost)
        seqs = {}
        for mode in ("off", "on"):
            os.environ[memledger.ENABLE_ENV] = "0" if mode == "off" else "1"
            decode_scheduler.reset()
            await _stream_one(client, payload(prompts[0]))
            itls, toks_all = [], []
            for p in prompts:
                toks, _, gaps = await _stream_one(client, payload(p))
                itls.extend(gaps)
                toks_all.append(toks)
            seqs[mode] = toks_all
            results[f"ledger_{mode}"] = {
                "itl_ms_p50": round(_pct(itls, 0.5), 3),
                "itl_ms_p99": round(_pct(itls, 0.99), 3),
            }
        results["itl_p50_delta_ms"] = round(
            results["ledger_on"]["itl_ms_p50"]
            - results["ledger_off"]["itl_ms_p50"], 3)
        parity_ok = seqs["off"] == seqs["on"]

        # -- phase 2: mixed tenants decoding while /memory/ attributes
        os.environ[memledger.ENABLE_ENV] = "1"
        decode_scheduler.reset()
        jobs = [(p, "mem-a" if i % 2 == 0 else "mem-b")
                for i, p in enumerate(prompts)]
        gen = asyncio.gather(*[_stream_one(client, payload(p, t))
                               for p, t in jobs])
        peak_tenants: dict = {}
        invariant_ok = True
        polls = 0
        while not gen.done():
            resp = await client.get("/memory/")
            mem = await resp.json()
            polls += 1
            for e in mem["engines"]:
                invariant_ok = invariant_ok and (
                    sum(e["pool_pages"].values()) == e["pool_pages_total"])
            tp = mem["tenant_pages"]
            if sum(tp.values()) > sum(peak_tenants.values() or [0]):
                peak_tenants = dict(tp)
            await asyncio.sleep(0.02)
        outs = await gen
        mixed_seqs = [toks for toks, _, _ in outs]
        parity_ok = parity_ok and mixed_seqs == seqs["on"]
        attribution_ok = (peak_tenants.get("mem-a", 0) > 0
                          and peak_tenants.get("mem-b", 0) > 0)
        results["attribution"] = {
            "polls": polls, "tenant_pages_peak": peak_tenants,
            "ok": attribution_ok}

        # -- final snapshot: a clean pool and zero lifetime leak counters
        resp = await client.get("/memory/")
        final = await resp.json()
        final.pop("engines", None)
        results["final_memory"] = final
        clean = (final["audit_failures"] == 0
                 and final["kv_pool_capacity_drops"] == 0
                 and final["unpin_underflows"] == 0)
        results["parity_ok"] = parity_ok
        results["invariant_ok"] = invariant_ok
        results["ok"] = bool(parity_ok and invariant_ok
                             and attribution_ok and clean)
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --sessions: hibernated-session resume TTFT across tiers vs cold prefill
# ---------------------------------------------------------------------------

async def _bench_sessions() -> dict:
    """Session hibernation / KV tiering workload (serve/tierstore.py).

    N sessions each generate once with a ``session_id`` — full
    prompt+generated KV hibernates at retirement and demotes off-device in
    the background — then every session is resumed (its full token history
    as the prompt) under four placements:

    - ``hbm``: right after retirement, the radix copy is still resident —
      the wake aliases pages with no import;
    - ``host``: after ``decode_scheduler.reset()`` destroyed the engine
      (and its radix tree) — the wake imports the pinned-host blob into a
      FRESH engine's radix cache;
    - ``disk``: same, after ``PENROZ_TIER_HOST_MB=0`` forced the spill all
      the way to the disk blob store;
    - ``cold``: same prompts with every session record deleted — the full
      re-prefill baseline the tiers have to beat.

    Greedy parity is asserted across all four placements per prompt.  One
    extra warm-up session per phase absorbs engine spin-up and XLA
    compilation so the timed TTFTs measure the wake path, not the first
    post-reset compile.  The headline gate: host-tier resume TTFT p50 at
    least 2x faster than cold re-prefill."""
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 512)
    # Default scale is where the tiering trade is real: prefill compute
    # (O(d^2) per token) well above the blob-import memcpy (O(d)) — at
    # toy scale the import cost would mask the recompute saving the
    # tiers exist to avoid.
    d = _env_i("PENROZ_BENCH_SERVING_D", 512)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 4)
    sessions = _env_i("PENROZ_BENCH_SESSIONS", 4)
    prompt_len = _env_i("PENROZ_BENCH_SESSION_PROMPT", 320)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 8)
    page = _env_i("PENROZ_BENCH_PREFIX_PAGE", 16)
    vocab = 512
    assert prompt_len + 2 * max_new <= block

    env = {
        decode_scheduler.ENABLE_ENV: "1",
        "PAGED_KV_CACHE": "1",
        "PENROZ_KV_PAGE_SIZE": str(page),
        "PENROZ_PREFIX_CACHE": "1",
        # room for every session's pages at once plus churn
        "PENROZ_PREFIX_CACHE_PAGES": str(
            4 * (sessions + 1) * (-(-block // page))),
    }
    saved = {k: os.environ.get(k)
             for k in (*env, "PENROZ_TIER_HOST_MB")}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(3)
    # index 0 is the per-phase warm-up session; 1..N are timed
    prompts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
               for _ in range(sessions + 1)]
    sids = [f"bench-sess-{i}" for i in range(sessions + 1)]

    def payload(prompt, session_id=None):
        body = {"model_id": "bench-sessions", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}
        if session_id:
            body["session_id"] = session_id
        return body

    async def wait_tier(tier, deadline_s=30.0):
        """Background demotion is asynchronous — poll /sessions/ until
        every record reached ``tier`` (or the deadline trips)."""
        deadline = time.perf_counter() + deadline_s
        while True:
            resp = await client.get("/sessions/")
            body = await resp.json()
            tiers = [s["tier"] for s in body["sessions"]]
            if tiers and all(t == tier for t in tiers):
                return body
            assert time.perf_counter() < deadline, (tier, body)
            await asyncio.sleep(0.05)

    async def tier_counters():
        resp = await client.get("/serving_stats/")
        stats = await resp.json()
        return {"promotions": dict(stats["tier_promotions"]),
                "by_tier": dict(stats["sessions_by_tier"]),
                "resident": stats["sessions_resident"]}

    def promo_delta(before, after):
        return {k: after["promotions"][k] - before["promotions"][k]
                for k in after["promotions"]}

    results: dict = {"mode": "sessions", "block_size": block,
                     "page_size": page, "sessions": sessions,
                     "prompt_len": prompt_len, "max_new_tokens": max_new,
                     "model_d": d, "model_depth": depth}
    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-sessions",
            "layers": _toy_gpt(d=d, vocab=vocab, block=block, depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        # -- hibernate: one generation per session id -------------------
        histories = []
        for p, sid in zip(prompts, sids):
            toks, _, _ = await _stream_one(client, payload(p, sid))
            histories.append(p + toks)
        listing = await wait_tier("host")
        results["hibernated"] = listing["sessions_resident"]
        results["nbytes_per_session"] = (
            listing["sessions"][0]["nbytes"] if listing["sessions"] else 0)

        outputs: dict = {}
        ttfts: dict = {}

        async def resume_phase(name):
            """Warm-up resume (session 0, untimed) then timed resumes of
            sessions 1..N; parity-checked against the other phases."""
            before = await tier_counters()
            await _stream_one(client, payload(histories[0]))
            outs, times = [], []
            for h in histories[1:]:
                toks, ttft_ms, _ = await _stream_one(client, payload(h))
                outs.append(toks)
                times.append(ttft_ms)
            outputs[name] = outs
            ttfts[name] = times
            results[f"resume_{name}"] = {
                "ttft_ms_p50": round(_pct(times, 0.5), 3),
                "ttft_ms_all": [round(t, 3) for t in times],
                "promotions_delta": promo_delta(before,
                                                await tier_counters()),
            }

        # -- hbm: radix copies still resident on the live engine --------
        await resume_phase("hbm")

        # -- host: fresh engine, blob import from pinned host RAM -------
        decode_scheduler.reset()
        await resume_phase("host")

        # -- disk: re-hibernate under a zero host cap (spills every blob
        # to the disk store), fresh engine again, import from disk ------
        os.environ["PENROZ_TIER_HOST_MB"] = "0"
        for h, sid in zip(histories, sids):
            await _stream_one(client, payload(h, sid))
        await wait_tier("disk")
        decode_scheduler.reset()
        await resume_phase("disk")

        # -- cold: no sessions at all, full re-prefill ------------------
        for sid in sids:
            resp = await client.delete(f"/sessions/{sid}")
            assert resp.status == 200, await resp.text()
        resp = await client.get("/sessions/")
        assert (await resp.json())["sessions_resident"] == 0
        decode_scheduler.reset()
        await resume_phase("cold")

        results["parity_ok"] = (
            outputs["hbm"] == outputs["host"] == outputs["disk"]
            == outputs["cold"])
        for tier in ("hbm", "host", "disk"):
            results[f"ttft_p50_speedup_{tier}_vs_cold"] = round(
                results["resume_cold"]["ttft_ms_p50"]
                / max(results[f"resume_{tier}"]["ttft_ms_p50"], 1e-9), 3)
        wakes = sessions  # timed resumes per warm phase
        promoted = sum(results["resume_host"]["promotions_delta"].values())
        results["promotion_hit_rate_host"] = round(
            (results["resume_host"]["promotions_delta"]["ok"]
             + results["resume_host"]["promotions_delta"]["partial"])
            / max(promoted, 1), 3) if promoted else 0.0
        results["wakes_per_phase"] = wakes
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        results["ok"] = bool(
            results["parity_ok"]
            and results["hibernated"] >= sessions
            and results["ttft_p50_speedup_host_vs_cold"] >= 2.0)
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --restart: crash-durable serving (journal replay + stream reconnect)
# ---------------------------------------------------------------------------

def _simulate_process_death():
    """What SIGKILL leaves behind, in-process: the journal file and the
    disk-tier blobs survive; every in-memory registry vanishes WITHOUT
    running a single drop/demote path.  (The real-subprocess SIGKILL
    variant lives in tests/test_journal.py — this bench measures the
    recovery timings, which need a shared process for a fair clock.)"""
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import journal, streams, tierstore
    with tierstore.TIERS._lock:
        tierstore.TIERS._sessions.clear()
        tierstore.TIERS._host.clear()
        tierstore.TIERS._index.clear()
    journal.JOURNAL.close()
    journal.reset()        # fresh-process counters; the FILE is untouched
    streams.reset()
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()


async def _bench_restart() -> dict:
    """Crash-durability workload (serve/journal.py + tierstore recovery +
    resumable streams).  Legs:

    1. **Hibernate**: N sessions generate once each with a write-ahead
       journal armed and ``PENROZ_TIER_HOST_MB=0`` so every blob lands in
       the disk store.
    2. **Warm-disk reference**: same-process resumes from the disk tier
       (fresh engine) — PR 17's ~195 ms path, re-measured on this machine
       so the restart gate is hardware-independent.
    3. **Restart**: the process "dies" (see _simulate_process_death) and
       a fresh ``create_app()`` replays the journal.  Reported:
       sessions_restored, journal_replay_ms.
    4. **Post-restart resume**: each session's full history re-submitted;
       the wake must promote the recovered disk blob at greedy parity.
       Headline gate: post-restart resume TTFT p50 within 1.5x of leg 2.
    5. **Reconnect**: R streams drop mid-flight and reattach with
       ``GET /generate/{id}/stream?from_seq`` — reconnect gap (close ->
       first replayed event) p50/p99, with exactly-once sequence coverage
       asserted on every cycle.
    """
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.serve import streams as streams_mod

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 512)
    d = _env_i("PENROZ_BENCH_SERVING_D", 512)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 4)
    sessions = _env_i("PENROZ_BENCH_SESSIONS", 4)
    prompt_len = _env_i("PENROZ_BENCH_SESSION_PROMPT", 320)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 8)
    page = _env_i("PENROZ_BENCH_PREFIX_PAGE", 16)
    reconnects = _env_i("PENROZ_BENCH_RECONNECTS", 8)
    vocab = 512
    assert prompt_len + 2 * max_new <= block

    durdir = tempfile.mkdtemp(prefix="penroz_bench_restart_")
    env = {
        decode_scheduler.ENABLE_ENV: "1",
        "PAGED_KV_CACHE": "1",
        "PENROZ_KV_PAGE_SIZE": str(page),
        "PENROZ_PREFIX_CACHE": "1",
        "PENROZ_PREFIX_CACHE_PAGES": str(
            4 * (sessions + 1) * (-(-block // page))),
        "PENROZ_TIER_HOST_MB": "0",           # demote straight to disk
        "PENROZ_TIER_DISK_PATH": os.path.join(durdir, "tier"),
        "PENROZ_JOURNAL_PATH": os.path.join(durdir, "serve.journal"),
        "PENROZ_JOURNAL_FSYNC": "batch",
        "PENROZ_STREAM_DETACH_MS": "60000",
        "PENROZ_STREAM_REPLAY": str(4 * max_new),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(11)
    # index 0 is the per-phase warm-up session; 1..N are timed
    prompts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
               for _ in range(sessions + 1)]
    sids = [f"bench-restart-{i}" for i in range(sessions + 1)]

    def payload(prompt, session_id=None):
        body = {"model_id": "bench-restart", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}
        if session_id:
            body["session_id"] = session_id
        return body

    async def wait_tier(tier, deadline_s=30.0):
        deadline = time.perf_counter() + deadline_s
        while True:
            resp = await client.get("/sessions/")
            body = await resp.json()
            tiers = [s["tier"] for s in body["sessions"]]
            if tiers and all(t == tier for t in tiers):
                return body
            assert time.perf_counter() < deadline, (tier, body)
            await asyncio.sleep(0.05)

    async def resume_phase(name, results):
        """Warm-up resume (session 0, untimed) then timed resumes of
        sessions 1..N via promote-on-match of the full history."""
        await _stream_one(client, payload(histories[0]))
        outs, times = [], []
        for h in histories[1:]:
            toks, ttft_ms, _ = await _stream_one(client, payload(h))
            outs.append(toks)
            times.append(ttft_ms)
        results[f"resume_{name}"] = {
            "ttft_ms_p50": round(_pct(times, 0.5), 3),
            "ttft_ms_all": [round(t, 3) for t in times]}
        return outs

    results: dict = {"mode": "restart", "block_size": block,
                     "page_size": page, "sessions": sessions,
                     "prompt_len": prompt_len, "max_new_tokens": max_new,
                     "model_d": d, "model_depth": depth}
    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-restart",
            "layers": _toy_gpt(d=d, vocab=vocab, block=block, depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()

        # -- leg 1: hibernate every session to the disk tier ------------
        histories = []
        for p, sid in zip(prompts, sids):
            toks, _, _ = await _stream_one(client, payload(p, sid))
            histories.append(p + toks)
        await wait_tier("disk")
        resp = await client.get("/serving_stats/")
        results["journal_pre_kill"] = (await resp.json())["journal"]

        # -- leg 2: same-process warm-disk reference (PR 17 path).  The
        # wakes import the disk blobs but do NOT consume the records
        # (match() journals a promote and leaves the tier alone), so the
        # disk store is still fully populated when the process "dies".
        decode_scheduler.reset()
        warm_out = await resume_phase("warm_disk", results)

        # -- leg 3: kill -9 and restart through create_app() ------------
        decode_scheduler.reset()
        await client.close()
        _simulate_process_death()
        t_restart = time.perf_counter()
        client = TestClient(TestServer(app_mod.create_app()))
        await client.start_server()
        results["restart_wall_ms"] = round(
            (time.perf_counter() - t_restart) * 1000.0, 3)
        resp = await client.get("/serving_stats/")
        stats = await resp.json()
        recovery = stats["restart_recovery"]
        results["restart_recovery"] = recovery
        results["sessions_restored"] = recovery.get("sessions_recovered", 0)
        results["journal_replay_ms"] = recovery.get("replay_ms", 0.0)
        resp = await client.get("/sessions/")
        listing = await resp.json()
        results["restored_by_tier"] = dict(listing["sessions_by_tier"])

        # -- leg 4: post-restart resume (recovered blobs, fresh engine) -
        post_out = await resume_phase("post_restart", results)
        resp = await client.get("/serving_stats/")
        promos = (await resp.json())["tier_promotions"]
        results["post_restart_promotions"] = dict(promos)
        results["parity_ok"] = post_out == warm_out
        warm = results["resume_warm_disk"]["ttft_ms_p50"]
        post = results["resume_post_restart"]["ttft_ms_p50"]
        results["restart_ttft_ratio"] = round(post / max(warm, 1e-9), 3)
        results["ref_warm_disk_ms_pr17"] = 195.0

        # -- leg 5: stream drop + from_seq reconnect, exactly once ------
        gaps, exactly_once = [], True
        for i in range(reconnects):
            rid = f"bench-reconn-{i}"
            body = dict(payload(prompts[1 + i % sessions][:64]),
                        stream=True)
            resp = await client.post("/generate/", json=body,
                                     headers={"X-Request-Id": rid})
            assert resp.status == 200, await resp.text()
            first = int(await resp.content.readline())
            t_drop = time.perf_counter()
            resp.close()
            # wait for the server to see the drop (detach) or finish
            deadline = time.perf_counter() + 10.0
            while True:
                sess = streams_mod.STREAMS.get(rid)
                if sess is None or sess.terminal \
                        or sess.detached_at is not None:
                    break
                assert time.perf_counter() < deadline, "no detach"
                await asyncio.sleep(0.005)
            r2 = await client.get(f"/generate/{rid}/stream",
                                  params={"from_seq": 1})
            assert r2.status == 200, await r2.text()
            seqs, vals, gap_ms = [], [], None
            while True:
                line = await r2.content.readline()
                if not line:
                    break
                if gap_ms is None:
                    gap_ms = (time.perf_counter() - t_drop) * 1000.0
                s, v = line.decode().strip().split(":", 1)
                seqs.append(int(s))
                vals.append(v)
            gaps.append(gap_ms if gap_ms is not None else float("inf"))
            exactly_once = exactly_once and bool(seqs) \
                and seqs == list(range(1, 1 + len(seqs))) \
                and vals[-1] == "done" \
                and len([first] + vals[:-1]) == max_new
        resp = await client.get("/serving_stats/")
        stream_stats = (await resp.json())["streams"]
        results["reconnect"] = {
            "cycles": reconnects,
            "gap_ms_p50": round(_pct(gaps, 0.5), 3),
            "gap_ms_p99": round(_pct(gaps, 0.99), 3),
            "gap_ms_all": [round(g, 3) for g in gaps],
            "exactly_once_ok": exactly_once,
            "detaches": stream_stats["detaches"],
            "resumes": stream_stats["resumes"],
            "expired": stream_stats["expired"]}
        resp = await client.get("/serving_stats/")
        results["journal_post_restart"] = (await resp.json())["journal"]

        results["ok"] = bool(
            results["parity_ok"]
            and exactly_once
            and results["sessions_restored"] >= sessions + 1
            and (results["restart_ttft_ratio"] <= 1.5
                 or post <= 1.5 * 195.0))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --chaos: one armed fault site under overload (scripts/chaos_matrix.sh)
# ---------------------------------------------------------------------------

async def _bench_chaos() -> dict:
    """Overload waves with ONE fault site armed (``PENROZ_BENCH_CHAOS_SITE``,
    ``site:raise@N`` via utils/faults.py), mixed-priority so the QoS
    preemption path runs too.  The contract chaos_matrix.sh enforces:

    - while armed, every response is 200/429/503/504 — plus 500 for the
      requests the injected crash itself fails (InjectedFault surfaces as
      a 500 to the victims of that one tick; anything else is a bug);
    - after the fault clears, a solo replay of every prompt is greedy
      token-identical to its pre-chaos baseline (``parity_ok``) — crash
      recovery must rebuild state, not corrupt it.

    Sites that never execute during a serving workload (ckpt.write,
    data.download) pass trivially: arming them must not disturb serving.
    """
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults

    site = os.environ.get("PENROZ_BENCH_CHAOS_SITE", "qos.preempt")
    at = _env_i("PENROZ_BENCH_CHAOS_AT", 3)
    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 128)
    rows = _env_i("PENROZ_BENCH_OVER_ROWS", 2)
    waves = _env_i("PENROZ_BENCH_OVER_WAVES", 2)
    offered = _env_i("PENROZ_BENCH_OVER_N", 8)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 12)
    env = {
        decode_scheduler.ENABLE_ENV: "1",
        decode_scheduler.MAX_ROWS_ENV: str(rows),
        decode_scheduler.MAX_QUEUE_ENV: "4",
        "PAGED_KV_CACHE": "1",
        "PENROZ_KV_PAGE_SIZE": "16",
        "PENROZ_PREFIX_CACHE": "1",
        "PENROZ_PREFIX_CACHE_PAGES": "64",
    }
    hybrid = site.startswith("ssm.")
    if site.startswith("disagg."):
        # the hand-off only executes with prefill replicas split out;
        # odd PENROZ_BENCH_CHAOS_AT ordinals crash an export, even ones
        # an import (each successful hand-off burns one of each)
        env["PENROZ_DISAGG_PREFILL"] = "1"
        env["PENROZ_DISAGG_PREFILL_REPLICAS"] = "1"
        if _env_i(decode_scheduler.REPLICAS_ENV, 1) < 2:
            env[decode_scheduler.REPLICAS_ENV] = "2"
    if site == "ssm.handoff":
        # the site fires mid-export only for archs with recurrent blocks
        # and only on the disagg hand-off path; transport pinned to the
        # host codec so each hand-off burns exactly one ordinal (the d2d
        # path would re-stage through the host and burn two)
        env["PENROZ_DISAGG_PREFILL"] = "1"
        env["PENROZ_DISAGG_PREFILL_REPLICAS"] = "1"
        env[decode_scheduler.REPLICAS_ENV] = "2"
        env[decode_scheduler.DISAGG_TRANSPORT_ENV] = "host"
    if site == "disagg.rebalance":
        # the flip only executes with the elastic rebalancer on; an
        # absurd shrink threshold makes every submit request a 2->1
        # prefill shrink.  Elastic stays OFF here and is switched on
        # together with the fault spec (env reads are per-call), so the
        # one possible shrink flip first runs WHILE armed: raise@1
        # crashes the first flip attempt and the retry at the next
        # drain boundary must succeed
        env[decode_scheduler.REPLICAS_ENV] = "3"
        env["PENROZ_DISAGG_PREFILL_REPLICAS"] = "2"
        env["PENROZ_DISAGG_ELASTIC"] = "0"
        env["PENROZ_DISAGG_REBALANCE_COOLDOWN_MS"] = "0"
        env["PENROZ_DISAGG_REBALANCE_DOWN"] = "1000000000"
    if site.startswith("pipe."):
        # the pipeline schedule only runs with a stage group configured;
        # the ragged unified dispatch is its prerequisite (the matrix
        # pins it, but arming pipe sites standalone must work too)
        env["PENROZ_SERVE_PIPE_STAGES"] = os.environ.get(
            "PENROZ_SERVE_PIPE_STAGES", "2")
        env["PENROZ_RAGGED_ATTENTION"] = "1"
    tier = site.startswith("tier.")
    journal_site = site.startswith("journal.")
    stream_site = site == "stream.resume"
    if tier or journal_site:
        # tier.demote / tier.promote only execute when sessions actually
        # hibernate and wake: small pages so the short bench prompts span
        # whole pages, session ids on every request (below), and the
        # chaos waves replay each baseline's FULL token history so the
        # promote-on-match import runs while armed
        env["PENROZ_KV_PAGE_SIZE"] = "4"
    if journal_site:
        # journal.append fires on every session register/demote/promote;
        # journal.replay only fires inside create_app()'s recovery — the
        # armed phase for that site is a double in-process restart (see
        # below), not a request wave.  Zero host cap pushes every blob
        # to the disk store so recovery has something to restore.
        jdir = tempfile.mkdtemp(prefix="penroz_chaos_journal_")
        env["PENROZ_JOURNAL_PATH"] = os.path.join(jdir, "serve.journal")
        env["PENROZ_JOURNAL_FSYNC"] = "always"
        env["PENROZ_TIER_DISK_PATH"] = os.path.join(jdir, "tier")
        env["PENROZ_TIER_HOST_MB"] = "0"
    if stream_site:
        # stream.resume fires at the top of every from_seq reattach: the
        # armed phase drops streaming clients mid-flight and reconnects;
        # a generous grace + ring keeps every drop resumable
        env["PENROZ_STREAM_DETACH_MS"] = "60000"
        env["PENROZ_STREAM_REPLAY"] = "64"
    if site == "tier.promote":
        # the import only executes once the radix copy is gone (a
        # radix-resident session wakes on the HBM fast path, no blob
        # read) — a tiny prefix cache makes each baseline session evict
        # its predecessors', so the armed wakes must import
        env["PENROZ_PREFIX_CACHE_PAGES"] = "8"
    saved = {k: os.environ.get(k) for k in env}
    saved[faults.ENV] = os.environ.get(faults.ENV)
    os.environ.update(env)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(7)
    prompts = [[int(t) for t in rng.integers(1, 255, 4 + (i % 4))]
               for i in range(offered)]
    # mixed-priority offered load: tail requests are interactive so the
    # row-full + interactive-queued preemption path actually executes
    klass = ["batch" if i < offered - 2 else "interactive"
             for i in range(offered)]

    sids = [f"chaos-{i}" if (tier or journal_site) else None
            for i in range(offered)]

    async def one(prompt, priority=None, session_id=None):
        body = {"model_id": "bench-chaos", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}
        if priority:
            body["priority"] = priority
        if session_id:
            body["session_id"] = session_id
        resp = await client.post("/generate/", json=body)
        return resp.status, (await resp.json() if resp.status != 204
                             else None)

    try:
        layers = (_toy_hybrid(d=128, depth=2, block=block) if hybrid
                  else _toy_gpt(d=128, depth=2, block=block))
        resp = await client.post("/model/", json={
            "model_id": "bench-chaos", "layers": layers,
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()

        baselines = {}
        for p, sid in zip(prompts, sids):
            status, body = await one(p, session_id=sid)
            assert status == 200, body
            baselines[tuple(p)] = body["tokens"]

        # Tier sites: the armed waves resume each baseline's session with
        # its full history as the prompt — every admission is a hibernated
        # wake (tier.promote fires mid-import) and every retirement
        # re-hibernates (tier.demote fires in the background spill).
        wave_prompts = ([baselines[tuple(p)] for p in prompts]
                        if tier or journal_site else prompts)

        extra: dict = {}
        if journal_site:
            # both journal sites need the baselines' blobs settled in the
            # disk store before arming (demotion is asynchronous)
            deadline = time.perf_counter() + 30.0
            while True:
                resp = await client.get("/sessions/")
                listing = await resp.json()
                tiers = [s["tier"] for s in listing["sessions"]]
                if tiers and all(t == "disk" for t in tiers):
                    break
                assert time.perf_counter() < deadline, listing
                await asyncio.sleep(0.05)

        os.environ[faults.ENV] = f"{site}:raise@{at}"
        if site == "disagg.rebalance":
            os.environ["PENROZ_DISAGG_ELASTIC"] = "1"
        faults.reset()
        statuses: dict = {}
        if site == "journal.replay":
            # the site fires inside create_app()'s journal replay: kill
            # the process in-bench and restart WHILE armed — the injected
            # crash must be contained (empty registry, disk blobs
            # untouched) — then restart again clean and require full
            # recovery before the parity replay below
            decode_scheduler.reset()
            await client.close()
            _simulate_process_death()
            client = TestClient(TestServer(app_mod.create_app()))
            await client.start_server()
            resp = await client.get("/serving_stats/")
            armed = (await resp.json())["restart_recovery"]
            extra["replay_errors_armed"] = armed.get("replay_errors", 0)
            extra["sessions_recovered_armed"] = armed.get(
                "sessions_recovered", 0)
            os.environ.pop(faults.ENV, None)
            faults.reset()
            decode_scheduler.reset()
            await client.close()
            _simulate_process_death()
            client = TestClient(TestServer(app_mod.create_app()))
            await client.start_server()
            resp = await client.get("/serving_stats/")
            clean = (await resp.json())["restart_recovery"]
            extra["sessions_recovered"] = clean.get("sessions_recovered", 0)
        elif stream_site:
            # drop a streaming client mid-flight, reattach with from_seq;
            # the injected crash 500s one reattach and the retry must
            # deliver the missed tokens exactly once
            from penroz_tpu.serve import streams as streams_mod
            extra["stream_resume_faults"] = 0
            exactly_once = True
            for i in range(2 * waves):
                rid = f"chaos-reconn-{i}"
                body = {"model_id": "bench-chaos",
                        "input": [prompts[i % offered]],
                        "block_size": block, "max_new_tokens": max_new,
                        "temperature": 0.0, "stream": True}
                resp = await client.post(
                    "/generate/", json=body,
                    headers={"X-Request-Id": rid})
                assert resp.status == 200, await resp.text()
                first = int(await resp.content.readline())
                resp.close()
                deadline = time.perf_counter() + 10.0
                while True:
                    sess = streams_mod.STREAMS.get(rid)
                    if sess is None or sess.terminal \
                            or sess.detached_at is not None:
                        break
                    assert time.perf_counter() < deadline, "no detach"
                    await asyncio.sleep(0.005)
                for attempt in range(2):
                    r2 = await client.get(f"/generate/{rid}/stream",
                                          params={"from_seq": 1})
                    statuses[r2.status] = statuses.get(r2.status, 0) + 1
                    if r2.status == 200:
                        break
                    extra["stream_resume_faults"] += 1
                    await r2.release()
                assert r2.status == 200, await r2.text()
                seqs, vals = [], []
                while True:
                    line = await r2.content.readline()
                    if not line:
                        break
                    s, v = line.decode().strip().split(":", 1)
                    seqs.append(int(s))
                    vals.append(v)
                exactly_once = exactly_once and bool(seqs) \
                    and seqs == list(range(1, 1 + len(seqs))) \
                    and vals[-1] == "done" \
                    and len([first] + vals[:-1]) == max_new
            extra["stream_exactly_once"] = exactly_once
            extra["stream_stats"] = streams_mod.STREAMS.stats()
        else:
            for _ in range(waves):
                results = await asyncio.gather(
                    *[one(p, k, sid)
                      for p, k, sid in zip(wave_prompts, klass, sids)])
                for status, _ in results:
                    statuses[status] = statuses.get(status, 0) + 1
        os.environ.pop(faults.ENV, None)
        faults.reset()

        allowed = {200, 429, 500, 503, 504}
        disallowed = {s: n for s, n in statuses.items() if s not in allowed}

        # breaker may still be cooling down after the injected crash —
        # wait it out before the parity replay (solo, fault cleared)
        deadline = time.perf_counter() + 30.0
        parity_ok = True
        for p in prompts:
            while True:
                status, body = await one(p)
                if status == 200:
                    parity_ok = parity_ok \
                        and body["tokens"] == baselines[tuple(p)]
                    break
                assert status == 503, (status, body)
                assert time.perf_counter() < deadline, "breaker stuck open"
                await asyncio.sleep(0.2)

        resp = await client.get("/serving_stats/")
        stats = await resp.json()
        return {
            "mode": "chaos", "site": site, "raise_at": at,
            "superstep": _env_i(decode_scheduler.SUPERSTEP_ENV, 8),
            "sched_mode": ("unified" if decode_scheduler.ragged_enabled()
                           else "phased"),
            "offered_requests": sum(statuses.values()),
            "statuses": {str(s): n for s, n in sorted(statuses.items())},
            "disallowed": {str(s): n for s, n in disallowed.items()},
            "crashes_total": stats.get("crashes_total", 0),
            "preemptions": stats.get("preemptions_total", 0),
            # disagg.handoff faults are CAUGHT (export/import failures
            # fall back to monolithic prefill), so the evidence they
            # fired is the failure counter, not a crash
            "disagg_imports": stats.get("disagg_imports", 0),
            "disagg_handoff_failures": stats.get(
                "disagg_handoff_failures", 0),
            # disagg.rebalance evidence: the crashed flip retried and
            # landed (>0), with the role registry still consistent
            "disagg_role_changes": stats.get("disagg_role_changes", 0),
            # tier.* evidence: sessions really hibernated and wakes really
            # ran the promote import while the site was armed
            "sessions_hibernated": stats.get("sessions_hibernated", 0),
            "session_promotions": stats.get("session_promotions", 0),
            "tier_promotions": stats.get("tier_promotions", {}),
            # journal.append evidence lives in journal.append_errors (the
            # failed append is contained, not a crash); journal.replay /
            # stream.resume evidence is in the `extra` keys filled by
            # their armed phases above
            "journal": stats.get("journal", {}),
            # pipe.handoff evidence: the caught fault re-staged through
            # the host (fallback counter); pipe.stage_crash evidence is
            # the ordinary crash/reset pair — whole-group recovery
            "pipe_stages": stats.get("pipe_stages", 1),
            "pipe_handoffs": stats.get("pipe_handoffs", 0),
            "pipe_handoff_host_fallbacks": stats.get(
                "pipe_handoff_host_fallbacks", 0),
            # ssm.* evidence: the arch really carried recurrent rows
            # (ssm.scan crashes surface as the ordinary crash/reset pair;
            # ssm.handoff failures land in disagg_handoff_failures)
            "ssm_state_bytes": stats.get("ssm_state_bytes", 0),
            "engine_resets": stats.get("engine_resets", 0),
            **extra,
            "parity_ok": parity_ok,
            "ok": (not disallowed and parity_ok
                   and extra.get("stream_exactly_once", True)
                   and ("sessions_recovered" not in extra
                        or extra["sessions_recovered"] >= offered)),
        }
    finally:
        decode_scheduler.reset()
        await client.close()
        faults.reset()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --pipeline: MPMD stage-partitioned decode (PENROZ_SERVE_PIPE_STAGES)
# ---------------------------------------------------------------------------

async def _bench_pipeline() -> dict:
    """Pipeline-parallel decode: the SAME greedy workload measured three
    ways — unpiped (``PENROZ_SERVE_PIPE_STAGES`` unset, the PR 18 serving
    path), S=1 (pipeline code path armed but degenerate — must be
    byte-identical to unpiped), and S=2 (stage-partitioned params +
    per-stage KV pools, token micro-batching between stages).

    Evidence the JSON carries:

    - ``parity_s1`` / ``parity_s2``: greedy token streams byte-identical
      to the unpiped baseline at both stage counts;
    - ``capacity``: the unpiped engine's KV pool bytes vs the largest
      single-stage pool at S=2 (from ``/memory/`` ``stage_pools``) — the
      full model's pool exceeds one stage's budget, i.e. S=2 serves a
      model sized past what one stage provisions;
    - ``bubble_fraction`` / ``pipe_stage_busy`` / ``pipe_handoffs``: the
      fill-drain bubble model from tick telemetry — stage-slot idleness
      over ``pipe_ticks * stages`` stage-slots, with zero host fallbacks
      on the healthy path.

    Scale knobs: the shared ``PENROZ_BENCH_SERVING_BLOCK/_D/_DEPTH``,
    ``PENROZ_BENCH_MAX_NEW``, ``PENROZ_BENCH_PIPE_STREAMS``."""
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 128)
    d = _env_i("PENROZ_BENCH_SERVING_D", 64)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 4)
    streams = _env_i("PENROZ_BENCH_PIPE_STREAMS", 4)
    prompt_len = _env_i("PENROZ_BENCH_PIPE_PROMPT", 12)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 32)
    vocab = 256
    assert prompt_len + max_new <= block
    assert depth % 2 == 0, "need an even layer count to split at S=2"

    env = {
        decode_scheduler.ENABLE_ENV: "1",
        decode_scheduler.MAX_ROWS_ENV: str(streams),
        "PAGED_KV_CACHE": "1",
        "PENROZ_RAGGED_ATTENTION": "1",
        "PENROZ_KV_PAGE_SIZE": "16",
    }
    saved = {k: os.environ.get(k)
             for k in (*env, "PENROZ_SERVE_PIPE_STAGES")}
    os.environ.update(env)
    os.environ.pop("PENROZ_SERVE_PIPE_STAGES", None)

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(17)
    prompts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
               for _ in range(streams)]
    warm_prompts = [[int(t) for t in rng.integers(1, vocab - 1, prompt_len)]
                    for _ in range(streams)]

    def payload(prompt):
        return {"model_id": "bench-pipe", "input": [prompt],
                "block_size": block, "max_new_tokens": max_new,
                "temperature": 0.0}

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-pipe",
            "layers": _toy_gpt(d=d, heads=4, vocab=vocab, block=block,
                               depth=depth),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()
        metrics_before = await _scrape_metrics(client)

        results: dict = {
            "mode": "pipeline", "block_size": block, "model_d": d,
            "model_depth": depth, "streams": streams,
            "prompt_len": prompt_len, "max_new": max_new,
        }
        seqs: dict = {}
        for phase, stages in (("unpiped", None), ("s1", 1), ("s2", 2)):
            if stages is None:
                os.environ.pop("PENROZ_SERVE_PIPE_STAGES", None)
            else:
                os.environ["PENROZ_SERVE_PIPE_STAGES"] = str(stages)
            decode_scheduler.reset()
            # warm with distinct prompts so measured streams pay no compiles
            await asyncio.gather(*[_stream_one(client, payload(p))
                                   for p in warm_prompts])
            outs = await asyncio.gather(*[_stream_one(client, payload(p))
                                          for p in prompts])
            seqs[phase] = [toks for toks, _, _ in outs]
            itls = [g for _, _, gaps in outs for g in gaps]
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            resp = await client.get("/memory/")
            mem = await resp.json()
            eng = mem["engines"][0] if mem.get("engines") else {}
            e_stats = stats["engines"][0] if stats.get("engines") else {}
            results[phase] = {
                "itl_ms_p50": (round(_pct(itls, 0.5), 3) if itls else None),
                "itl_ms_p99": (round(_pct(itls, 0.99), 3) if itls else None),
                "pipe_stages": stats.get("pipe_stages", 1),
                "pipe_ticks": stats.get("pipe_ticks", 0),
                "pipe_microblocks": e_stats.get("pipe_microblocks", 0),
                "pipe_bubble_fraction": stats.get("pipe_bubble_fraction"),
                "pipe_stage_busy": e_stats.get("pipe_stage_busy", {}),
                "pipe_handoffs": stats.get("pipe_handoffs", 0),
                "pipe_handoff_host_fallbacks": stats.get(
                    "pipe_handoff_host_fallbacks", 0),
                "kv_pool_bytes": (int(eng["hbm_bytes"].get("kv_values", 0))
                                  + int(eng["hbm_bytes"].get("kv_scales", 0))
                                  if eng.get("hbm_bytes") else 0),
                "stage_pools": eng.get("stage_pools", []),
            }

        results["parity_s1"] = seqs["s1"] == seqs["unpiped"]
        results["parity_s2"] = seqs["s2"] == seqs["unpiped"]
        # Capacity: the whole model's KV pool vs ONE stage's provisioned
        # pool at S=2.  Each stage only budgets pages for its own layer
        # slice, so the unpiped pool (all layers on one stage) must not
        # fit inside the largest single-stage pool.
        full_bytes = results["unpiped"]["kv_pool_bytes"]
        stage_bytes = [int(sp["kv_pool_bytes"])
                       for sp in results["s2"]["stage_pools"]]
        results["capacity"] = {
            "full_model_kv_pool_bytes": full_bytes,
            "s2_stage_kv_pool_bytes": stage_bytes,
            "exceeds_single_stage_pool": bool(
                stage_bytes and full_bytes > max(stage_bytes)),
        }
        s2 = results["s2"]
        bubble = s2["pipe_bubble_fraction"]
        pipe_ok = (
            s2["pipe_stages"] == 2 and s2["pipe_ticks"] > 0
            and bubble is not None and 0.0 <= bubble < 1.0
            and s2["pipe_handoffs"] > 0
            and s2["pipe_handoff_host_fallbacks"] == 0
            and set(s2["pipe_stage_busy"]) == {"0", "1"}
            and results["unpiped"]["pipe_ticks"] == 0)
        results["bubble_fraction"] = bubble
        results["ok"] = bool(
            results["parity_s1"] and results["parity_s2"] and pipe_ok
            and results["capacity"]["exceeds_single_stage_pool"])
        results["metrics_delta"] = _metrics_delta(
            metrics_before, await _scrape_metrics(client))
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# --hybrid: constant-memory sequence backends vs the all-attention twin
# ---------------------------------------------------------------------------

async def _bench_hybrid() -> dict:
    """Hybrid (attention + ssm blocks) vs its all-attention twin at the
    same d/depth/block — the capacity claim of the constant-memory
    backends PR, measured two ways:

    - capacity: per-row sequence-state bytes (KV pool rows + recurrent
      planes, REAL allocated states, not formulas) and the max concurrent
      rows a fixed HBM budget holds.  Headline gate: ``row_ratio`` —
      hybrid must fit >= 1.5x the rows of the twin (every ssm block
      replaces an O(T) KV pool with an O(1) state);
    - serving: the same greedy workload through the unified scheduler for
      both archs, with live ssm stats evidence (the hybrid engine reports
      recurrent rows/bytes, the twin reports zero) and per-arch
      throughput/ITL.

    Scale knobs: ``PENROZ_BENCH_SERVING_BLOCK/_D/_DEPTH``,
    ``PENROZ_BENCH_HBM_BUDGET_MB``, ``PENROZ_BENCH_REQUESTS``,
    ``PENROZ_BENCH_MAX_NEW``.
    """
    import jax.numpy as jnp
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.models.model import CompiledArch
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    block = _env_i("PENROZ_BENCH_SERVING_BLOCK", 256)
    d = _env_i("PENROZ_BENCH_SERVING_D", 128)
    depth = _env_i("PENROZ_BENCH_SERVING_DEPTH", 4)
    budget_mb = _env_i("PENROZ_BENCH_HBM_BUDGET_MB", 64)
    requests = _env_i("PENROZ_BENCH_REQUESTS", 4)
    max_new = _env_i("PENROZ_BENCH_MAX_NEW", 16)
    env = {
        decode_scheduler.ENABLE_ENV: "1",
        decode_scheduler.MAX_ROWS_ENV: "4",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)

    twins = {
        "attn": _toy_gpt(d=d, depth=depth, block=block),
        "hybrid": _toy_hybrid(d=d, depth=depth, block=block, ssm_every=2),
    }

    # -- capacity: real per-row state bytes at this block size ------------
    capacity = {}
    for name, layers in twins.items():
        arch = CompiledArch.get(layers)
        state = KV.create_kv_state(arch.kv_specs, 1, block, jnp.float32,
                                   ssm_specs=arch.ssm_specs)
        per_row = sum(state.hbm_components().values())
        capacity[name] = {
            "kv_layers": len(arch.kv_specs),
            "ssm_layers": len(arch.ssm_specs),
            "per_row_state_bytes": int(per_row),
            "max_rows_at_budget": int(budget_mb * 2**20 // per_row),
        }
    row_ratio = (capacity["hybrid"]["max_rows_at_budget"]
                 / max(capacity["attn"]["max_rows_at_budget"], 1))

    # -- serving: the same workload through the unified scheduler ---------
    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(11)
    prompts = [[int(t) for t in rng.integers(1, 255, 6 + (i % 3))]
               for i in range(requests)]
    serving = {}
    try:
        for name, layers in twins.items():
            model_id = f"bench-{name}"
            resp = await client.post("/model/", json={
                "model_id": model_id, "layers": layers,
                "optimizer": {"sgd": {"lr": 0.1}}})
            assert resp.status == 200, await resp.text()

            async def one(prompt):
                resp = await client.post("/generate/", json={
                    "model_id": model_id, "input": [prompt],
                    "block_size": block, "max_new_tokens": max_new,
                    "temperature": 0.0})
                assert resp.status == 200, await resp.text()
                return await resp.json()

            t0 = time.perf_counter()
            outs = await asyncio.gather(*[one(p) for p in prompts])
            elapsed = time.perf_counter() - t0
            # solo replay parity: the batched scheduler output must match
            # each request run alone (same contract the tests enforce)
            parity_ok = True
            for p, out in zip(prompts, outs):
                solo = await one(p)
                parity_ok = parity_ok and solo["tokens"] == out["tokens"]
            resp = await client.get("/serving_stats/")
            stats = await resp.json()
            entry = next(e for e in stats["engines"]
                         if e["model_id"] == model_id)
            serving[name] = {
                "requests": requests, "max_new": max_new,
                "wall_s": round(elapsed, 3),
                "tokens_per_sec": round(requests * max_new / elapsed, 2),
                "itl_ms_p50": entry.get("itl_ms_p50"),
                "ttft_ms_p99": entry.get("ttft_ms_p99"),
                "ssm_rows_now": entry.get("ssm_rows", 0),
                "ssm_state_bytes": entry.get("ssm_state_bytes", 0),
                "parity_ok": parity_ok,
            }
    finally:
        decode_scheduler.reset()
        await client.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    return {
        "mode": "hybrid", "block": block, "d": d, "depth": depth,
        "hbm_budget_mb": budget_mb,
        "capacity": capacity,
        "row_ratio": round(row_ratio, 3),
        "serving": serving,
        "ok": (row_ratio >= 1.5
               and serving["hybrid"]["ssm_state_bytes"] > 0
               and serving["attn"]["ssm_state_bytes"] == 0
               and all(s["parity_ok"] for s in serving.values())),
    }


def _emit(results: dict):
    line = json.dumps(results)
    print(line)
    out = os.environ.get("PENROZ_BENCH_JSON_OUT")
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")


def main():
    args = [a for a in sys.argv[1:]
            if a not in ("--shared-prefix", "--overload", "--speculative",
                         "--multi-adapter", "--multistep", "--mixed-slo",
                         "--chaos", "--ragged", "--memory", "--replicas",
                         "--disagg", "--disagg-elastic", "--sessions",
                         "--restart", "--pipeline", "--hybrid")]
    shared_prefix = "--shared-prefix" in sys.argv[1:]
    overload = "--overload" in sys.argv[1:]
    replicas = "--replicas" in sys.argv[1:]
    speculative = "--speculative" in sys.argv[1:]
    multi_adapter = "--multi-adapter" in sys.argv[1:]
    multistep = "--multistep" in sys.argv[1:]
    mixed_slo = "--mixed-slo" in sys.argv[1:]
    chaos = "--chaos" in sys.argv[1:]
    sessions = "--sessions" in sys.argv[1:]
    restart = "--restart" in sys.argv[1:]
    ragged = "--ragged" in sys.argv[1:]
    memory = "--memory" in sys.argv[1:]
    disagg = "--disagg" in sys.argv[1:]
    disagg_elastic = "--disagg-elastic" in sys.argv[1:]
    pipeline = "--pipeline" in sys.argv[1:]
    hybrid = "--hybrid" in sys.argv[1:]
    if os.environ.get("PENROZ_BENCH_JSON_OUT"):
        # resolve before the chdir below so a relative path lands where the
        # caller (bench_watch.sh) expects it
        os.environ["PENROZ_BENCH_JSON_OUT"] = os.path.abspath(
            os.environ["PENROZ_BENCH_JSON_OUT"])
    # Isolated checkpoint dirs: the benchmark must not touch repo models.
    # PENROZ_SHM_PATH is pinned too (before any penroz import reads it) —
    # the shm write-through copy otherwise leaks blobs across bench runs
    # (an adapter_* blob in the real /dev/shm would 409 the next run's
    # POST /adapters/).
    workdir = tempfile.mkdtemp(prefix="penroz_bench_serving_")
    os.environ.setdefault("PENROZ_SHM_PATH", workdir)
    os.chdir(workdir)
    if overload:
        _emit(asyncio.run(_bench_overload()))
        return
    if replicas:
        _emit(asyncio.run(_bench_replicas()))
        return
    if shared_prefix:
        _emit(asyncio.run(_bench_shared_prefix()))
        return
    if speculative:
        _emit(asyncio.run(_bench_speculative()))
        return
    if multi_adapter:
        _emit(asyncio.run(_bench_multi_adapter()))
        return
    if multistep:
        _emit(asyncio.run(_bench_multistep()))
        return
    if mixed_slo:
        _emit(asyncio.run(_bench_mixed_slo()))
        return
    if chaos:
        _emit(asyncio.run(_bench_chaos()))
        return
    if sessions:
        _emit(asyncio.run(_bench_sessions()))
        return
    if restart:
        _emit(asyncio.run(_bench_restart()))
        return
    if ragged:
        _emit(asyncio.run(_bench_ragged()))
        return
    if memory:
        _emit(asyncio.run(_bench_memory()))
        return
    if disagg_elastic:
        _emit(asyncio.run(_bench_disagg_elastic()))
        return
    if pipeline:
        _emit(asyncio.run(_bench_pipeline()))
        return
    if hybrid:
        _emit(asyncio.run(_bench_hybrid()))
        return
    if disagg:
        _emit(asyncio.run(_bench_disagg()))
        return
    concurrency = int(args[0]) if len(args) > 0 else 8
    max_new = int(args[1]) if len(args) > 1 else 48
    block = int(os.environ.get("PENROZ_BENCH_SERVING_BLOCK", "256"))
    _emit(asyncio.run(_bench(concurrency, max_new, block)))


if __name__ == "__main__":
    main()
