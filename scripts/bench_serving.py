"""Serving benchmark: N concurrent /generate/ requests, continuous-batching
scheduler ON vs OFF, against the real aiohttp app in-process.

Measures the acceptance shape of the scheduler directly: with the scheduler
enabled, N concurrent greedy requests share one batch-N decode step per
token, so their wall-clock approaches one request's — while the legacy path
runs N independent batch-1 decode loops.  Greedy outputs are asserted
token-identical between the serial-off baseline and every other phase
(``parity_ok``), so the speedup is never bought with wrong tokens.

Prints ONE JSON line, e.g.::

  {"concurrency": 8, "max_new_tokens": 48,
   "scheduler_off": {"serial_s": ..., "concurrent_s": ...},
   "scheduler_on":  {"serial_s": ..., "concurrent_s": ...},
   "concurrent_speedup_on_vs_off": 3.1,
   "concurrent_on_vs_serial_off": 4.9,
   "parity_ok": true, "serving_stats": {...}}

CPU by default (``PENROZ_BENCH_SERVING_PLATFORM`` overrides); run from the
repo root: ``python scripts/bench_serving.py [concurrency] [max_new]``.
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS",
                      os.environ.get("PENROZ_BENCH_SERVING_PLATFORM", "cpu"))

import asyncio  # noqa: E402
import json  # noqa: E402
import tempfile  # noqa: E402
import time  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _toy_gpt(d=256, heads=8, vocab=512, block=256, depth=4):
    """Small-but-real GPT stack (attention + KV cache on the hot path) —
    sized so a forward's compute dominates per-dispatch overhead on CPU,
    the regime the scheduler exists for (a micro-model measures dispatch
    floors, not batching)."""
    return ([{"summation": [
                {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
                 "normal": {"mean": 0.0, "std": 0.02}},
                {"position": {"num_embeddings": block, "embedding_dim": d},
                 "normal": {"mean": 0.0, "std": 0.02}}]}]
            + [{"residual": [
                {"sequential": [
                    {"layernorm": {"normalized_shape": d}},
                    {"linear": {"in_features": d, "out_features": 3 * d},
                     "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                    {"attention": {"num_heads": heads, "dropout": 0.0}},
                    {"linear": {"in_features": d, "out_features": d}}]},
                {"sequential": [
                    {"layernorm": {"normalized_shape": d}},
                    {"linear": {"in_features": d, "out_features": 4 * d}},
                    {"gelu": {}},
                    {"linear": {"in_features": 4 * d, "out_features": d}}]},
               ]} for _ in range(depth)]
            + [{"layernorm": {"normalized_shape": d}},
               {"linear": {"in_features": d, "out_features": vocab,
                           "bias": False}},
               {"softmaxlast": {"dim": -1}}])


async def _bench(concurrency: int, max_new: int, block: int) -> dict:
    import numpy as np
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    from penroz_tpu.serve import decode_scheduler

    client = TestClient(TestServer(app_mod.create_app()))
    await client.start_server()
    rng = np.random.default_rng(0)
    prompts = [[int(t) for t in rng.integers(1, 255, 8 + (i % 5))]
               for i in range(concurrency)]

    async def generate(prompt):
        resp = await client.post("/generate/", json={
            "model_id": "bench-serving", "input": [prompt],
            "block_size": block, "max_new_tokens": max_new,
            "temperature": 0.0})
        body = await resp.json()
        assert resp.status == 200, body
        return body["tokens"]

    try:
        resp = await client.post("/model/", json={
            "model_id": "bench-serving", "layers": _toy_gpt(block=block),
            "optimizer": {"sgd": {"lr": 0.1}}})
        assert resp.status == 200, await resp.text()

        results: dict = {"concurrency": concurrency,
                         "max_new_tokens": max_new, "block_size": block}
        baselines = None
        parity_ok = True
        for mode in ("off", "on"):
            os.environ[decode_scheduler.ENABLE_ENV] = \
                "1" if mode == "on" else "0"
            # Warm every prompt shape per mode: prefill programs retrace per
            # prompt length, and the timed rounds must compare steady-state
            # serving, not who pays the compiles.
            for p in prompts:
                await generate(p)
            t0 = time.perf_counter()
            serial = [await generate(p) for p in prompts]
            serial_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            concurrent = await asyncio.gather(*[generate(p)
                                                for p in prompts])
            concurrent_s = time.perf_counter() - t0
            if baselines is None:
                baselines = serial
            parity_ok = parity_ok and serial == baselines \
                and list(concurrent) == baselines
            total_tokens = concurrency * max_new
            results[f"scheduler_{mode}"] = {
                "serial_s": round(serial_s, 3),
                "concurrent_s": round(concurrent_s, 3),
                "concurrent_tokens_per_sec": round(
                    total_tokens / concurrent_s, 1),
            }
        off, on = results["scheduler_off"], results["scheduler_on"]
        results["concurrent_speedup_on_vs_off"] = round(
            off["concurrent_s"] / on["concurrent_s"], 3)
        results["concurrent_on_vs_serial_off"] = round(
            off["serial_s"] / on["concurrent_s"], 3)
        results["parity_ok"] = parity_ok
        resp = await client.get("/serving_stats/")
        stats = await resp.json()
        stats.pop("engines", None)
        results["serving_stats"] = stats
        return results
    finally:
        decode_scheduler.reset()
        await client.close()
        os.environ.pop(decode_scheduler.ENABLE_ENV, None)


def main():
    concurrency = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    max_new = int(sys.argv[2]) if len(sys.argv) > 2 else 48
    block = int(os.environ.get("PENROZ_BENCH_SERVING_BLOCK", "256"))
    # Isolated checkpoint dirs: the benchmark must not touch repo models.
    workdir = tempfile.mkdtemp(prefix="penroz_bench_serving_")
    os.chdir(workdir)
    results = asyncio.run(_bench(concurrency, max_new, block))
    print(json.dumps(results))


if __name__ == "__main__":
    main()
