#!/usr/bin/env python
"""Tier-1 gate wall-clock budget report from pytest ``--durations`` output.

The tier-1 gate (ROADMAP.md) runs the whole not-slow suite under
``timeout -k 10 1080`` — an 18-minute hard wall.  Every PR that adds
serving tests nibbles at that budget, and until now the "which tests
should move to the slow lane" call was eyeballed from raw pytest output.
This script turns it into a report:

    # from a saved log (the gate already tees /tmp/_t1.log):
    python -m pytest tests/ -q -m 'not slow' --durations=50 2>&1 \
        | tee /tmp/_t1.log
    python scripts/tier1_budget.py /tmp/_t1.log

    # or pipe it:
    python scripts/tier1_budget.py - < /tmp/_t1.log

    # or let the script run pytest itself (slow — the full gate):
    python scripts/tier1_budget.py --run

It parses the ``slowest N durations`` table (``12.34s call
tests/x.py::test_y`` lines), merges the setup/call/teardown phases per
test, and prints:

- the top-N tests by total wall (``--top``, default 15) with their
  phase split and share of the measured wall;
- per-file subtotals (the "which module is the problem" view);
- the projected gate wall vs the timeout: pytest's own ``in N.NNs``
  summary when present (that IS the gate wall), else the durations sum
  (a lower bound — pytest only reports the slowest N phases).

Exit status: 0 when the projected wall fits inside the budget scaled by
``--headroom`` (default 0.85 — an 18-min gate should cruise at ~15 min,
the last 15% absorbs CI jitter), 2 when it does not, 1 on a parse error.
No dependencies beyond the standard library; the report is plain text so
it can ride in a PR description verbatim.
"""

from __future__ import annotations

import argparse
import re
import subprocess
import sys

BUDGET_S = 1080.0  # the gate's `timeout -k 10 1080` wall (18 min)

# "12.34s call     tests/test_x.py::test_y[param]"
_DUR_RE = re.compile(
    r"^\s*(?P<secs>\d+(?:\.\d+)?)s\s+"
    r"(?P<phase>setup|call|teardown)\s+"
    r"(?P<test>\S+)\s*$")
# pytest's tail summary: "123 passed, 4 failed, ... in 456.78s"
_WALL_RE = re.compile(r"\bin (?P<secs>\d+(?:\.\d+)?)s\b")


def parse_durations(lines) -> tuple[dict, float | None]:
    """``{test_id: {phase: secs}}`` plus the suite wall from the tail
    summary (None when the log has no ``in N.NNs`` line)."""
    tests: dict = {}
    wall = None
    for line in lines:
        m = _DUR_RE.match(line)
        if m:
            phases = tests.setdefault(m.group("test"), {})
            phases[m.group("phase")] = (phases.get(m.group("phase"), 0.0)
                                        + float(m.group("secs")))
            continue
        m = _WALL_RE.search(line)
        if m:
            wall = float(m.group("secs"))  # last one wins (re-runs)
    return tests, wall


def _fmt_row(name, total, phases, share):
    split = "/".join(f"{phases.get(p, 0.0):.1f}"
                     for p in ("setup", "call", "teardown"))
    return f"{total:8.1f}s  {share:5.1%}  [{split}]  {name}"


def report(tests: dict, wall, top: int, budget: float,
           headroom: float, out=sys.stdout) -> int:
    if not tests:
        print("no `--durations` table found — rerun pytest with "
              "--durations=50 (or higher)", file=sys.stderr)
        return 1
    totals = {t: sum(p.values()) for t, p in tests.items()}
    measured = sum(totals.values())
    ranked = sorted(totals.items(), key=lambda kv: -kv[1])

    print(f"tier-1 budget report — {len(tests)} tests in the durations "
          f"table, {measured:.1f}s measured", file=out)
    print(f"\ntop {min(top, len(ranked))} by wall "
          "(total  share  [setup/call/teardown]):", file=out)
    for name, total in ranked[:top]:
        print(_fmt_row(name, total, tests[name],
                       total / measured if measured else 0.0), file=out)

    by_file: dict = {}
    for name, total in totals.items():
        by_file[name.split("::", 1)[0]] = (
            by_file.get(name.split("::", 1)[0], 0.0) + total)
    print("\nper-file subtotals:", file=out)
    for path, total in sorted(by_file.items(), key=lambda kv: -kv[1]):
        print(f"{total:8.1f}s  {path}", file=out)

    projected = wall if wall is not None else measured
    basis = ("suite wall (pytest tail summary)" if wall is not None
             else "durations sum — LOWER BOUND, pytest reports only the "
                  "slowest phases; rerun with a larger --durations for a "
                  "tighter floor")
    limit = budget * headroom
    verdict = "OK" if projected <= limit else "OVER"
    print(f"\nprojected gate wall: {projected:.1f}s of {budget:.0f}s "
          f"({projected / budget:.1%} of the timeout; basis: {basis})",
          file=out)
    print(f"headroom target: <= {limit:.0f}s "
          f"({headroom:.0%} of budget) -> {verdict}", file=out)
    if verdict == "OVER":
        over = projected - limit
        print(f"move ~{over:.0f}s of tests to the slow lane "
              "(@pytest.mark.slow) — start from the top of the table",
              file=out)
    return 0 if verdict == "OK" else 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Tier-1 gate wall-clock budget report from pytest "
                    "--durations output")
    ap.add_argument("log", nargs="?", default=None,
                    help="pytest log file to parse ('-' = stdin); "
                         "omit with --run")
    ap.add_argument("--run", action="store_true",
                    help="run the tier-1 gate command itself "
                         "(JAX_PLATFORMS=cpu, --durations) and parse "
                         "its output")
    ap.add_argument("--top", type=int, default=15,
                    help="rows in the slowest-tests table (default 15)")
    ap.add_argument("--durations", type=int, default=50,
                    help="--durations value for --run (default 50)")
    ap.add_argument("--budget", type=float, default=BUDGET_S,
                    help=f"gate timeout, seconds (default {BUDGET_S:.0f})")
    ap.add_argument("--headroom", type=float, default=0.85,
                    help="pass threshold as a fraction of budget "
                         "(default 0.85)")
    args = ap.parse_args(argv)

    if args.run:
        import os
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "tests/", "-q", "-m",
             "not slow", "--continue-on-collection-errors",
             f"--durations={args.durations}", "-p", "no:cacheprovider"],
            capture_output=True, text=True, env=env)
        lines = (proc.stdout + proc.stderr).splitlines()
    elif args.log is None:
        ap.error("either a log file (or '-') or --run is required")
    elif args.log == "-":
        lines = sys.stdin.read().splitlines()
    else:
        with open(args.log) as f:
            lines = f.read().splitlines()

    tests, wall = parse_durations(lines)
    return report(tests, wall, args.top, args.budget, args.headroom)


if __name__ == "__main__":
    sys.exit(main())
