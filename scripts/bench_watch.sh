#!/bin/bash
# Persistent accelerator watcher: probe the backend in short-lived child
# processes; on the first success, run the full bench with per-phase
# partials written into the repo (BENCH_PARTIAL.json) and the final line
# into BENCH_MIDROUND.out.  A pool window that opens for five minutes
# mid-round is converted into committed evidence instead of being missed
# (rounds 2 and 3 both ended rc=3 with zero driver-captured numbers).
set -u
cd "$(dirname "$0")/.."
mkdir -p logs
PROBE_S="${PENROZ_WATCH_PROBE_S:-120}"
SLEEP_S="${PENROZ_WATCH_SLEEP_S:-60}"
while true; do
  if timeout "$PROBE_S" python -c \
      "import jax; d=jax.devices(); print('BACKEND_OK', d[0].device_kind, len(d), flush=True)" \
      >> logs/bench_watch.log 2>&1; then
    echo "$(date -u +%FT%TZ) backend up -> running bench" >> logs/bench_watch.log
    PENROZ_BENCH_PARTIAL=BENCH_PARTIAL.json PENROZ_BENCH_WAIT_S=300 \
      python bench.py > BENCH_MIDROUND.out 2>> logs/bench_watch.log
    rc=$?
    echo "$(date -u +%FT%TZ) bench rc=$rc" >> logs/bench_watch.log
    if [ "$rc" -eq 0 ]; then
      exit 0
    fi
  fi
  sleep "$SLEEP_S"
done
