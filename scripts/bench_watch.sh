#!/bin/bash
# Persistent accelerator watcher: probe the backend in short-lived child
# processes; on every success, run the full bench with per-phase partials
# written into the repo (BENCH_PARTIAL.json), snapshot the result to a
# round-stamped artifact, and COMMIT it.  Then re-arm: a pool that opens
# twice yields two captures (rounds 2 and 3 both ended rc=3 with zero
# driver-captured numbers; round 4's single-shot watcher fired once and
# the final driver capture still missed).  Evidence must land in git the
# moment it exists.
set -u
cd "$(dirname "$0")/.."
mkdir -p logs
PROBE_S="${PENROZ_WATCH_PROBE_S:-120}"
SLEEP_S="${PENROZ_WATCH_SLEEP_S:-60}"
RESLEEP_S="${PENROZ_WATCH_RESLEEP_S:-1800}"   # between successful re-runs
ROUND="${PENROZ_ROUND:-05}"
SNAP="BENCH_MIDROUND_r${ROUND}.json"

# Soak-run serving observability: with PENROZ_WATCH_SERVING_URL pointing at
# a live server (e.g. http://127.0.0.1:8000), poll /serving_stats/ in the
# background and append timestamped JSON lines to logs/serving_stats.jsonl —
# continuous-batching occupancy/throughput regressions become visible in
# the same artifact stream as the bench captures.
SERVING_URL="${PENROZ_WATCH_SERVING_URL:-}"
SERVING_POLL_S="${PENROZ_WATCH_SERVING_POLL_S:-60}"
if [ -n "$SERVING_URL" ]; then
  (
    while true; do
      if out=$(curl -fsS --max-time 10 "${SERVING_URL%/}/serving_stats/" \
                 2>>logs/bench_watch.log); then
        printf '{"t":"%s","serving":%s}\n' "$(date -u +%FT%TZ)" "$out" \
          >> logs/serving_stats.jsonl
      fi
      sleep "$SERVING_POLL_S"
    done
  ) &
  SERVING_POLL_PID=$!
  trap '[ -n "${SERVING_POLL_PID:-}" ] && kill "$SERVING_POLL_PID" 2>/dev/null' EXIT
  echo "$(date -u +%FT%TZ) polling ${SERVING_URL%/}/serving_stats/ every ${SERVING_POLL_S}s (pid $SERVING_POLL_PID)" >> logs/bench_watch.log
fi

attempt=0
while true; do
  if timeout "$PROBE_S" python -c \
      "import jax; d=jax.devices(); print('BACKEND_OK', d[0].device_kind, len(d), flush=True)" \
      >> logs/bench_watch.log 2>&1; then
    attempt=$((attempt + 1))
    echo "$(date -u +%FT%TZ) backend up -> running bench (attempt $attempt)" >> logs/bench_watch.log
    PENROZ_BENCH_PARTIAL=BENCH_PARTIAL.json PENROZ_BENCH_WAIT_S=300 \
      timeout 3600 python bench.py > BENCH_MIDROUND.out 2>> logs/bench_watch.log
    rc=$?
    echo "$(date -u +%FT%TZ) bench rc=$rc" >> logs/bench_watch.log
    if [ "$rc" -ne 0 ]; then
      # Even a died/timed-out run leaves per-phase metrics in the
      # partial — commit the evidence rather than waiting for a clean
      # pass that may never come (r02/r03 ended with zero numbers).
      git add -- BENCH_PARTIAL.json >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: partial capture (rc=$rc)" \
          -- BENCH_PARTIAL.json >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) partial committed (rc=$rc)" >> logs/bench_watch.log
    fi
    # Serving-stack capture alongside the training bench: the shared-prefix
    # workload (chunked prefill + radix prefix cache) emits its own JSON
    # artifact via PENROZ_BENCH_JSON_OUT.  Opt-in (adds minutes per pass);
    # failures must not block the main capture.
    if [ "${PENROZ_WATCH_SHARED_PREFIX:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_SHARED_PREFIX_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --shared-prefix \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_SHARED_PREFIX_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: shared-prefix serving capture" \
          -- "BENCH_SHARED_PREFIX_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) shared-prefix capture committed" >> logs/bench_watch.log
    fi
    # Speculative-decoding capture (same shape as the shared-prefix hook):
    # tokens/decode-step + accept rate with spec on vs off.  Opt-in;
    # failures must not block the main capture.
    if [ "${PENROZ_WATCH_SPEC:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_SPEC_DECODE_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --speculative \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_SPEC_DECODE_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: speculative-decoding capture" \
          -- "BENCH_SPEC_DECODE_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) speculative capture committed" >> logs/bench_watch.log
    fi
    # Compiled multi-step decode capture (same shape as the shared-prefix
    # hook): single-row mean ITL + tokens/dispatch at superstep 1 vs 4 vs 8
    # with greedy parity.  Opt-in; failures must not block the main capture.
    if [ "${PENROZ_WATCH_MULTISTEP:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_MULTISTEP_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --multistep \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_MULTISTEP_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: multi-step decode capture" \
          -- "BENCH_MULTISTEP_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) multi-step capture committed" >> logs/bench_watch.log
    fi
    # SLO-tiered QoS capture (same shape as the shared-prefix hook):
    # interactive p99 TTFT under a batch flood, FIFO vs WFQ+preemption,
    # plus the tenant-quota offender/victim split.  Opt-in; failures must
    # not block the main capture.
    if [ "${PENROZ_WATCH_QOS:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_QOS_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --mixed-slo \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_QOS_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: mixed-SLO QoS capture" \
          -- "BENCH_QOS_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) mixed-SLO QoS capture committed" >> logs/bench_watch.log
    fi
    # Ragged unified-attention capture (same shape as the shared-prefix
    # hook): mixed-traffic ITL + tokens/dispatch, paged-unified vs
    # contiguous-phased, with greedy parity.  Opt-in; failures must not
    # block the main capture.
    if [ "${PENROZ_WATCH_RAGGED:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_RAGGED_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --ragged \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_RAGGED_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: ragged unified-attention capture" \
          -- "BENCH_RAGGED_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) ragged capture committed" >> logs/bench_watch.log
    fi
    # Disaggregated-prefill capture (same shape as the shared-prefix
    # hook): decode ITL + long-prompt TTFT + hand-off latency with
    # PENROZ_DISAGG_PREFILL off vs on over a 2-replica group, greedy
    # parity gated.  Opt-in; failures must not block the main capture.
    if [ "${PENROZ_WATCH_DISAGG:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_DISAGG_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --disagg \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_DISAGG_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: disaggregated-prefill capture" \
          -- "BENCH_DISAGG_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) disaggregated-prefill capture committed" >> logs/bench_watch.log
    fi
    # D2D hand-off + elastic-roles capture (same shape as the
    # shared-prefix hook): hand-off p50/p99 host vs d2d transport, plus
    # prefill-burst -> decode-burst ITL elastic vs pinned with role-flip
    # evidence.  Opt-in; failures must not block the main capture.
    if [ "${PENROZ_WATCH_D2D:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_D2D_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --disagg-elastic \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_D2D_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: d2d hand-off + elastic-roles capture" \
          -- "BENCH_D2D_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) d2d hand-off capture committed" >> logs/bench_watch.log
    fi
    # Capacity-ledger capture (same shape as the shared-prefix hook):
    # ledger on/off ITL delta + mixed-tenant /memory/ attribution under
    # PENROZ_MEMLEDGER_STRICT=1.  Opt-in; failures must not block the
    # main capture.
    if [ "${PENROZ_WATCH_MEMORY:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_MEM_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --memory \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_MEM_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: capacity-ledger capture" \
          -- "BENCH_MEM_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) capacity-ledger capture committed" >> logs/bench_watch.log
    fi
    # Replica-router capture (same shape as the shared-prefix hook):
    # goodput-vs-replicas curve under overload (shed rate, per-wave
    # goodput, prefix-affinity hit rate) with greedy parity across
    # widths.  Opt-in; failures must not block the main capture.
    if [ "${PENROZ_WATCH_REPLICAS:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_SHARD_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --replicas \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_SHARD_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: replica-router goodput capture" \
          -- "BENCH_SHARD_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) replica-router capture committed" >> logs/bench_watch.log
    fi
    # Session hibernation / KV tiering capture (same shape as the
    # shared-prefix hook): resume TTFT per tier (hbm radix hit, host blob
    # import, disk blob import) vs cold re-prefill, with greedy parity
    # across all placements and the promotion hit rate.  Opt-in; failures
    # must not block the main capture.
    if [ "${PENROZ_WATCH_SESSIONS:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_TIER_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --sessions \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_TIER_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: session-tiering resume capture" \
          -- "BENCH_TIER_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) session-tiering capture committed" >> logs/bench_watch.log
    fi
    # Crash-durability capture: journal replay ms, sessions restored
    # across a simulated kill -9, post-restart resume TTFT vs the
    # in-run warm-disk reference, and stream reconnect-gap p99.
    # Opt-in; failures must not block the main capture.
    if [ "${PENROZ_WATCH_RESTART:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_RESTART_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --restart \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_RESTART_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: restart-durability capture" \
          -- "BENCH_RESTART_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) restart-durability capture committed" >> logs/bench_watch.log
    fi
    # Multi-tenant LoRA capture (same shape as the shared-prefix hook):
    # mixed-adapter ITL/wall vs per-adapter serial groups + parity.
    # Opt-in; failures must not block the main capture.
    if [ "${PENROZ_WATCH_LORA:-0}" = "1" ]; then
      PENROZ_BENCH_JSON_OUT="$PWD/BENCH_LORA_r${ROUND}.json" \
        timeout 1800 python scripts/bench_serving.py --multi-adapter \
          >> logs/bench_watch.log 2>&1 \
        && git add -- "BENCH_LORA_r${ROUND}.json" \
          >> logs/bench_watch.log 2>&1 \
        && git commit -m "bench watcher: multi-adapter LoRA capture" \
          -- "BENCH_LORA_r${ROUND}.json" >> logs/bench_watch.log 2>&1 \
        && echo "$(date -u +%FT%TZ) multi-adapter capture committed" >> logs/bench_watch.log
    fi
    if [ "$rc" -eq 0 ]; then
      python - "$SNAP" "$attempt" <<'EOF' 2>> logs/bench_watch.log
import json, sys, time
snap, attempt = sys.argv[1], int(sys.argv[2])
with open("BENCH_PARTIAL.json") as fh:
    partial = json.load(fh)
out = {"rc": 0,
       "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
       "round": int(snap.split("_r")[1].split(".")[0]),
       "attempt": f"watcher run {attempt}",
       "metric": "gpt2-124M train tokens/sec/chip",
       "unit": "tokens/sec/chip"}
out.update(partial)
with open(snap, "w") as fh:
    json.dump(out, fh, indent=1)
EOF
      # Commit ONLY the bench artifacts.  `git add` first: the
      # round-stamped snapshot starts untracked and a pathspec-mode
      # commit of an untracked file fails outright.  Retry covers a
      # foreground git operation holding the lock at this instant.
      committed=0
      for _ in 1 2; do
        if git add -- "$SNAP" BENCH_PARTIAL.json BENCH_MIDROUND.out \
              >> logs/bench_watch.log 2>&1 \
            && git commit -m "bench watcher: on-chip capture (attempt $attempt, rc=0)" \
              -- "$SNAP" BENCH_PARTIAL.json BENCH_MIDROUND.out >> logs/bench_watch.log 2>&1; then
          committed=1
          break
        fi
        sleep 10
      done
      if [ "$committed" -eq 1 ]; then
        echo "$(date -u +%FT%TZ) snapshot committed -> $SNAP; re-arming in ${RESLEEP_S}s" >> logs/bench_watch.log
      else
        echo "$(date -u +%FT%TZ) COMMIT FAILED for $SNAP (left in worktree); re-arming in ${RESLEEP_S}s" >> logs/bench_watch.log
      fi
      sleep "$RESLEEP_S"
      continue
    fi
  fi
  sleep "$SLEEP_S"
done
