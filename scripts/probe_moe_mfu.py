"""On-chip probe: why does capacity MoE dispatch measure ~= dense?

All timing syncs via float() host transfers (block_until_ready is
unreliable over the axon relay — see bench.py).  Phase order: first
reproduce the headline train number as a sanity check (if it's far off
the 104578 tok/s captured in BENCH_MIDROUND_r04.json, the pool is
degraded and every number in this file is suspect), then dense-vs-
capacity MoE stacks, then capacity dispatch-group variants.

Writes each result to scripts/probe_results.json as it lands.
Throwaway instrumentation, not part of the framework.
"""
import json
import os
import time

import jax
import numpy as np

OUT = os.path.join(os.path.dirname(__file__), "probe_results.json")
results = {}


def emit(**kv):
    results.update(kv)
    with open(OUT, "w") as fh:
        json.dump(results, fh, indent=1, sort_keys=True)
    print("probe:", kv, flush=True)


def sanity_train():
    from __graft_entry__ import OPTIMIZER, _gpt2_dsl
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch
    import bench as B

    mapper = Mapper(_gpt2_dsl(depth=12, d=768, block=1024, heads=12),
                    OPTIMIZER)
    arch = CompiledArch.get(mapper.layers)
    params, _ = mapper.init_params(arch.mods, seed=0)
    params = jax.device_put(params, jax.devices()[0])
    tps, _ = B.bench_train(arch, mapper, params, batch=8, block=1024,
                           steps_per_call=4, warmup=2, timed=4)
    emit(sanity_headline_tps=round(tps, 1))
    return tps


def moe_variants():
    import bench as B
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch
    from penroz_tpu.ops import modules as M
    from __graft_entry__ import OPTIMIZER

    def run(dispatch, group=None, top_k=2, tag=""):
        if group is not None:
            M.MixtureOfExperts.DISPATCH_GROUP = group
        try:
            # same stack shape as the shipped bench_moe_dispatch
            d, experts, depth, batch, block = 512, 8, 4, 8, 512
            layers = [{"summation": [
                {"embedding": {"num_embeddings": 50304,
                               "embedding_dim": d},
                 "normal": {"mean": 0.0, "std": 0.02}},
                {"position": {"num_embeddings": block, "embedding_dim": d},
                 "normal": {"mean": 0.0, "std": 0.02}}]}]
            layers += [{"residual": [
                {"sequential": [
                    {"layernorm": {"normalized_shape": d}},
                    {"linear": {"in_features": d, "out_features": 3 * d},
                     "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                    {"attention": {"num_heads": 8, "dropout": 0.0}},
                    {"linear": {"in_features": d, "out_features": d}}]},
                {"sequential": [
                    {"layernorm": {"normalized_shape": d}},
                    {"moe": {"in_features": d, "intermediate_size": 4 * d,
                             "num_experts": experts, "top_k": top_k,
                             "dispatch": dispatch}}]}]}
                for _ in range(depth)]
            layers += [{"layernorm": {"normalized_shape": d}},
                       {"linear": {"in_features": d, "out_features": 50304,
                                   "bias": False}},
                       {"softmax": {"dim": -1}}]
            mapper = Mapper(layers, OPTIMIZER)
            arch = CompiledArch.get(mapper.layers)
            params, buffers = mapper.init_params(arch.mods, seed=0)
            tps, _ = B.bench_train(arch, mapper, params, batch=batch,
                                   block=block, steps_per_call=2,
                                   warmup=2, timed=6, buffers=buffers)
            emit(**{f"moe_{tag or dispatch}_tps": round(tps, 1)})
        finally:
            M.MixtureOfExperts.DISPATCH_GROUP = 512

    run("dense")
    run("capacity", group=512, tag="cap_g512")
    run("capacity", group=2048, tag="cap_g2048")
    run("capacity", group=4096, tag="cap_g4096")
    run("capacity", group=512, top_k=1, tag="cap_k1_g512")
    run("dense", top_k=1, tag="dense_k1")


if __name__ == "__main__":
    emit(device=str(jax.devices()[0].device_kind),
         ts=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    sanity_train()
    moe_variants()
