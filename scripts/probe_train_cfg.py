"""On-chip probe: headline train throughput vs batch / steps_per_call.

float()-synced via bench_train; each result lands in
scripts/probe_results.json immediately.  Throwaway instrumentation.
"""
import json
import os

import jax

OUT = os.path.join(os.path.dirname(__file__), "probe_results.json")
try:
    results = json.load(open(OUT))
except (OSError, ValueError):
    results = {}


def emit(**kv):
    results.update(kv)
    with open(OUT, "w") as fh:
        json.dump(results, fh, indent=1, sort_keys=True)
    print("probe:", kv, flush=True)


def main():
    from __graft_entry__ import OPTIMIZER, _gpt2_dsl
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch
    import bench as B

    for batch, steps in [(8, 4), (16, 4), (16, 2), (24, 2), (32, 2)]:
        mapper = Mapper(_gpt2_dsl(depth=12, d=768, block=1024, heads=12),
                        OPTIMIZER)
        arch = CompiledArch.get(mapper.layers)
        params, _ = mapper.init_params(arch.mods, seed=0)
        params = jax.device_put(params, jax.devices()[0])
        try:
            tps, _ = B.bench_train(arch, mapper, params, batch=batch,
                                   block=1024, steps_per_call=steps,
                                   warmup=2, timed=4)
            emit(**{f"train_b{batch}_s{steps}_tps": round(tps, 1)})
        except Exception as exc:  # noqa: BLE001
            emit(**{f"train_b{batch}_s{steps}_error": str(exc)[:160]})
            break


if __name__ == "__main__":
    main()
